"""Slot-based continuous batching over the ragged decode stack.

The lockstep :func:`~tree_attention_tpu.models.decode.generate` decodes one
batch whose rows start, step and stop together — requests with different
prompt lengths, arrival times or stop points cannot share it, so aggregate
tokens/sec dies at real traffic. This engine holds a **fixed batch of S cache
slots** (one :class:`~tree_attention_tpu.models.decode.KVCache` of batch S
with per-slot lengths) plus a request queue, and runs a tick loop:

1. **Admit** — every free slot takes the oldest pending request whose
   arrival time has passed: the prompt is prefilled into a slot-shaped
   side cache (one compile per padded prompt bucket) and inserted into the
   slot's region of the batch cache (k/v rows, per-slot length, first
   sampled token).
2. **Step** — ONE compiled decode step advances every live slot: the
   ragged ``forward_step`` writes each slot's new row at its own offset and
   masks each slot's unwritten tail independently. Dead slots ride along
   (static shapes) but their lengths are frozen and their tokens held, so
   occupancy changes never recompile.
3. **Retire** — a slot whose request hit EOS or its token budget frees
   immediately and is refilled on the same tick.

The slot lifecycle is therefore ``free -> (admit/prefill) -> live ->
(EOS | budget) -> free``, and the one compiled step serves every mixture of
slot states. Works on one device and on a sequence-sharded mesh (the cache
is seq-sharded; per-slot offsets ride the tree merge unchanged).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tree_attention_tpu import obs
from tree_attention_tpu.models.decode import (
    KVCache,
    QuantKVCache,
    _sample,
    forward_step,
    init_cache,
    quantize_cache,
)
from tree_attention_tpu.models.transformer import Params, TransformerConfig
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving")

# Serving observability. Occupancy/queue metrics are host-loop truths
# (execution-true, not trace-time): the loop sets/observes them as slots
# change hands; token/request counters count work the engine finished.
_SLOTS_OCCUPIED = obs.gauge(
    "serving_slots_occupied",
    "live slots in the serving batch (set once per tick)",
)
_QUEUE_WAIT = obs.histogram(
    "serving_queue_wait_seconds",
    "wall seconds a request waited between becoming visible and admission",
)
_TOKENS = obs.counter(
    "serving_tokens_total",
    "tokens decoded for live slots by executed serving ticks",
)
_REQUESTS = obs.counter(
    "serving_requests_total",
    "requests the engine finished, by outcome",
    labels=("outcome",),
)


@dataclasses.dataclass
class Request:
    """One generation request for the serving loop.

    ``arrival_tick`` is synthetic-trace time in decode ticks: the request
    becomes visible to the scheduler once the loop's tick counter reaches
    it (0 = already queued at start). ``eos_id`` stops generation early
    when sampled (the EOS token is included in the output).
    """

    uid: int
    prompt: Sequence[int]
    max_new_tokens: int
    arrival_tick: int = 0
    eos_id: Optional[int] = None


@dataclasses.dataclass
class RequestResult:
    uid: int
    tokens: List[int]
    prompt_len: int
    arrival_tick: int
    admit_tick: int
    finish_tick: int
    queue_wait_s: float
    completion_s: float  # visible -> finished, wall seconds
    outcome: str  # "eos" | "max_tokens"


@dataclasses.dataclass
class ServeReport:
    """One serve() run: per-request results plus aggregate accounting."""

    results: List[RequestResult]
    ticks: int
    wall_s: float
    tokens_generated: int
    mean_occupancy: float  # live slots per executed decode tick

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s > 0 else 0.0

    def completion_percentiles(self) -> Dict[str, float]:
        cs = sorted(r.completion_s for r in self.results)
        if not cs:
            return {"p50_s": 0.0, "p95_s": 0.0}
        pick = lambda p: cs[min(len(cs) - 1, int(p * (len(cs) - 1) + 0.5))]
        return {"p50_s": pick(0.50), "p95_s": pick(0.95)}

    def as_dict(self) -> Dict[str, Any]:
        waits = sorted(r.queue_wait_s for r in self.results)
        return {
            "requests": len(self.results),
            "ticks": self.ticks,
            "wall_s": round(self.wall_s, 4),
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_per_sec, 1),
            "mean_occupancy": round(self.mean_occupancy, 2),
            "queue_wait_p50_s": round(waits[len(waits) // 2], 4) if waits else 0.0,
            **{k: round(v, 4) for k, v in self.completion_percentiles().items()},
        }


def synthetic_trace(
    n_requests: int,
    *,
    prompt_len: int = 32,
    prompt_jitter: int = 0,
    max_new_tokens: int = 16,
    arrival_every: int = 0,
    vocab_size: int = 256,
    seed: int = 0,
    eos_id: Optional[int] = None,
) -> List[Request]:
    """A reproducible request trace: random prompts, optional length jitter,
    arrivals every ``arrival_every`` ticks (0 = all queued at start)."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n_requests):
        lo = max(1, prompt_len - prompt_jitter)
        hi = prompt_len + prompt_jitter
        plen = int(rng.integers(lo, hi + 1))
        reqs.append(Request(
            uid=i,
            prompt=rng.integers(0, vocab_size, size=plen).astype(np.int32),
            max_new_tokens=max_new_tokens,
            arrival_tick=i * arrival_every,
            eos_id=eos_id,
        ))
    return reqs


def _bucket(n: int, cap: int, floor: int = 8) -> int:
    """Pad a prompt length up to a power-of-two bucket (bounded compiles:
    one prefill program per bucket, not per distinct prompt length)."""
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)


class SlotServer:
    """Continuous-batching engine: S slots, a queue, one compiled step.

    Args:
      params / cfg: the model served.
      slots: batch size S of the slot cache — the max concurrent requests.
      cache_len: per-slot KV capacity; every admitted request needs
        ``prompt_len + max_new_tokens <= cache_len``.
      mesh (+ axis names): sequence-shard the slot cache over a mesh; the
        ragged decode step runs the tree merge per tick.
      quantize: serve from an int8 cache — each admit prefills exactly then
        quantizes that slot's rows under its own frozen per-channel scales
        (the quantize-after-prefill contract, per slot).
      temperature / seed: sampling (0 = greedy, the deterministic default).
    """

    def __init__(
        self,
        params: Params,
        cfg: TransformerConfig,
        *,
        slots: int,
        cache_len: int,
        mesh: Optional[Mesh] = None,
        quantize: bool = False,
        quant_kernel: str = "q8q",
        temperature: float = 0.0,
        seed: int = 0,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.cache_len = cache_len
        self.mesh = mesh
        self.quantize = quantize
        self.quant_kernel = quant_kernel
        self.temperature = float(temperature)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        self._key = jax.random.PRNGKey(seed)

        kw = {"mesh": mesh} if mesh is not None else {}
        self._fs_kw = dict(kw)
        # The per-request prefill runs on a B=1 mini cache, which cannot
        # shard over a data axis (1 does not divide it) — and needs no
        # data parallelism anyway; the batched per-tick step keeps the
        # full mesh spec.
        self._prefill_kw = (
            dict(kw, data_axis=None) if mesh is not None else {}
        )
        cache: Union[KVCache, QuantKVCache] = init_cache(
            cfg, slots, cache_len, **kw
        )
        if quantize:
            cache = quantize_cache(cache)  # empty prefix -> fallback scales
        self.cache = cache
        self.tok = jnp.zeros((slots,), jnp.int32)

        # Host mirror of slot state (the scheduler's view; device state is
        # the cache + tok + the live mask shipped each tick).
        self._slot_req: List[Optional[Request]] = [None] * slots
        self._slot_tokens: List[List[int]] = [[] for _ in range(slots)]
        self._slot_admit: List[Tuple[int, float]] = [(0, 0.0)] * slots

        # jax.jit caches one executable per padded-prompt bucket shape,
        # so a single jitted prefill serves every bucket (bounded
        # compiles); note the jit caches are per INSTANCE (bound methods),
        # so a fresh server recompiles — bench/serving.py warms the same
        # server it times. The tick loop reassigns self.cache/self.tok
        # from each call's outputs, so the old buffers are donated — the
        # per-tick step updates the (L,S,Hkv,Tmax,D) cache in place
        # instead of copying it (backends without donation just copy).
        self._prefill = jax.jit(self._prefill_fn)
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0, 1))
        self._step = jax.jit(self._step_fn, donate_argnums=(1, 2))

    # -- compiled pieces --------------------------------------------------

    def _sample(self, logits: jax.Array, key: jax.Array) -> jax.Array:
        # The ONE sampling definition is models.decode._sample — the
        # token-for-token parity contract with generate() depends on the
        # engine never growing its own variant.
        return _sample(logits, self.temperature, key)

    def _prefill_fn(self, params, prompt, plen, key):
        """Prefill one request into a fresh slot-shaped B=1 cache.

        ``prompt`` is padded to its bucket; rows at positions >= plen are
        pad garbage, so after the step they are zeroed — the inserted slot
        (and, under ``quantize``, its frozen per-channel scales) is then
        bit-identical to an unpadded prefill, and one compile serves the
        whole bucket.
        """
        cfg = self.cfg
        shape = (cfg.n_layers, 1, cfg.n_kv_heads, self.cache_len, cfg.d_head)
        mini = KVCache(
            k=jnp.zeros(shape, cfg.dtype),
            v=jnp.zeros(shape, cfg.dtype),
            length=jnp.zeros((1,), jnp.int32),
        )
        logits, mini = forward_step(params, prompt, mini, cfg,
                                    **self._prefill_kw)
        valid = (
            jnp.arange(self.cache_len, dtype=jnp.int32) < plen
        )[None, None, None, :, None]
        k = jnp.where(valid, mini.k, 0)
        v = jnp.where(valid, mini.v, 0)
        last = lax.dynamic_index_in_dim(logits, plen - 1, axis=1,
                                        keepdims=False)  # (1, V)
        tok = self._sample(last, key)[0]
        if self.quantize:
            qc = quantize_cache(KVCache(k=k, v=v, length=mini.length))
            return qc.k, qc.v, qc.k_scale, qc.v_scale, tok
        return k, v, tok

    def _insert_fn(self, cache, tok_vec, slot, payload, plen):
        """Place a prefilled B=1 cache into slot ``slot`` of the batch cache
        (k/v rows, per-slot length, first token) — one compile, any slot."""
        if self.quantize:
            k_new, v_new, ks_new, vs_new, first = payload
        else:
            k_new, v_new, first = payload
        put = lambda buf, new: lax.dynamic_update_index_in_dim(
            buf, new[:, 0], slot, axis=1
        )
        length = lax.dynamic_update_index_in_dim(
            cache.length, jnp.asarray(plen, jnp.int32), slot, axis=0
        )
        if self.quantize:
            new_cache = QuantKVCache(
                k=put(cache.k, k_new), v=put(cache.v, v_new),
                k_scale=put(cache.k_scale, ks_new),
                v_scale=put(cache.v_scale, vs_new),
                length=length,
            )
        else:
            new_cache = KVCache(
                k=put(cache.k, k_new), v=put(cache.v, v_new), length=length
            )
        tok_vec = lax.dynamic_update_index_in_dim(tok_vec, first, slot, axis=0)
        return new_cache, tok_vec

    def _step_fn(self, params, tok, cache, live, key):
        """One decode tick for the whole batch: ragged forward_step, sample,
        then freeze dead slots (length restored, token held) so occupancy
        changes are data, not shape."""
        kw = dict(self._fs_kw)
        if self.quantize:
            kw["quant_kernel"] = self.quant_kernel
        logits, new_cache = forward_step(params, tok[:, None], cache,
                                         self.cfg, **kw)
        key, sub = jax.random.split(key)
        nxt = self._sample(logits[:, -1], sub)
        length = jnp.where(live, new_cache.length, cache.length)
        new_cache = dataclasses.replace(new_cache, length=length)
        nxt = jnp.where(live, nxt, tok)
        return nxt, new_cache, key

    # -- scheduler --------------------------------------------------------

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _validate(self, req: Request) -> None:
        plen = len(req.prompt)
        if plen < 1:
            raise ValueError(f"request {req.uid}: empty prompt")
        if req.max_new_tokens < 1:
            # The prefill itself samples one token, so a zero budget
            # is unservable — same contract as generate().
            raise ValueError(
                f"request {req.uid}: max_new_tokens must be >= 1, "
                f"got {req.max_new_tokens}"
            )
        if plen + req.max_new_tokens > self.cache_len:
            raise ValueError(
                f"request {req.uid}: prompt {plen} + max_new "
                f"{req.max_new_tokens} exceeds slot capacity {self.cache_len}"
            )

    def _admit(self, req: Request, slot: int, tick: int,
               visible_at: float) -> float:
        # Queue wait ends the moment the scheduler takes the request —
        # BEFORE its prefill runs (prefill, including a first-bucket jit
        # compile, is service time, not queueing).
        waited = max(time.monotonic() - visible_at, 0.0)
        plen = len(req.prompt)
        bucket = _bucket(plen, self.cache_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = np.asarray(req.prompt, np.int32)
        self._key, sub = jax.random.split(self._key)
        payload = self._prefill(self.params, jnp.asarray(padded),
                                jnp.int32(plen), sub)
        self.cache, self.tok = self._insert(
            self.cache, self.tok, jnp.int32(slot), payload, plen
        )
        first = int(payload[-1])
        self._slot_req[slot] = req
        self._slot_tokens[slot] = [first]
        self._slot_admit[slot] = (tick, visible_at)
        if obs.REGISTRY.enabled:
            _QUEUE_WAIT.observe(waited)
            _TOKENS.inc()  # the prefill's first sampled token
        return waited

    def _retire(self, slot: int, tick: int, outcome: str,
                results: List[RequestResult]) -> None:
        req = self._slot_req[slot]
        admit_tick, visible_at = self._slot_admit[slot]
        now = time.monotonic()
        results.append(RequestResult(
            uid=req.uid,
            tokens=list(self._slot_tokens[slot]),
            prompt_len=len(req.prompt),
            arrival_tick=req.arrival_tick,
            admit_tick=admit_tick,
            finish_tick=tick,
            queue_wait_s=0.0,  # filled by serve() from its visible ledger
            completion_s=max(now - visible_at, 0.0),
            outcome=outcome,
        ))
        self._slot_req[slot] = None
        self._slot_tokens[slot] = []
        if obs.REGISTRY.enabled:
            _REQUESTS.labels(outcome=outcome).inc()

    def serve(self, requests: Sequence[Request],
              max_ticks: Optional[int] = None) -> ServeReport:
        """Run the tick loop until every request has finished.

        Requests are admitted in arrival order (FIFO per arrival tick);
        ``max_ticks`` bounds runaway loops (raises if work remains)."""
        for r in requests:
            self._validate(r)
        pending = deque(sorted(requests, key=lambda r: (r.arrival_tick, r.uid)))
        results: List[RequestResult] = []
        visible_wall: Dict[int, float] = {}
        wait_ledger: Dict[int, float] = {}
        tick = 0
        decode_ticks = 0
        occupancy = 0
        tokens = 0
        t0 = time.monotonic()

        while pending or any(r is not None for r in self._slot_req):
            if max_ticks is not None and tick >= max_ticks:
                raise RuntimeError(
                    f"serve() exceeded max_ticks={max_ticks} with "
                    f"{len(pending)} pending request(s)"
                )
            now = time.monotonic()
            for r in pending:  # sorted by arrival_tick — stop at the future
                if r.arrival_tick > tick:
                    break
                visible_wall.setdefault(r.uid, now)

            # Admit: oldest visible request per free slot; a retire this
            # tick already freed its slot, so refill happens immediately.
            free = self._free_slots()
            while free and pending and pending[0].arrival_tick <= tick:
                req = pending.popleft()
                slot = free.pop(0)
                vis = visible_wall.setdefault(req.uid, now)
                wait_ledger[req.uid] = self._admit(req, slot, tick, vis)
                first = self._slot_tokens[slot][0]
                if (req.eos_id is not None and first == req.eos_id):
                    # The prefill's own sample already ended the request.
                    self._retire(slot, tick, "eos", results)
                    free.append(slot)
                elif req.max_new_tokens <= 1:
                    self._retire(slot, tick, "max_tokens", results)
                    free.append(slot)

            live_idx = [i for i, r in enumerate(self._slot_req)
                        if r is not None]
            if obs.REGISTRY.enabled:
                _SLOTS_OCCUPIED.set(len(live_idx))
            if not live_idx:
                if not pending:
                    # The admit phase retired everything it admitted
                    # (max_new_tokens=1 / prefill-sampled EOS) and drained
                    # the queue: done.
                    break
                # Nothing running: fast-forward trace time to the next
                # arrival instead of spinning empty decode steps.
                tick = max(tick + 1, min(r.arrival_tick for r in pending))
                continue

            live = np.zeros((self.slots,), bool)
            live[live_idx] = True
            self.tok, self.cache, self._key = self._step(
                self.params, self.tok, self.cache, jnp.asarray(live),
                self._key,
            )
            toks_host = np.asarray(self.tok)  # fence: per-tick host sync
            decode_ticks += 1
            occupancy += len(live_idx)

            for i in live_idx:
                req = self._slot_req[i]
                tok_i = int(toks_host[i])
                self._slot_tokens[i].append(tok_i)
                tokens += 1
                if obs.REGISTRY.enabled:
                    _TOKENS.inc()
                if req.eos_id is not None and tok_i == req.eos_id:
                    self._retire(i, tick, "eos", results)
                elif len(self._slot_tokens[i]) >= req.max_new_tokens:
                    self._retire(i, tick, "max_tokens", results)
            tick += 1

        wall = time.monotonic() - t0
        for res in results:
            res.queue_wait_s = wait_ledger.get(res.uid, 0.0)
        # Prefill-sampled first tokens count toward the total.
        tokens += sum(1 for _ in results)
        log.info(
            "served %d request(s): %d tokens over %d decode tick(s), "
            "%.1f tok/s, mean occupancy %.2f/%d",
            len(results), tokens, decode_ticks,
            tokens / wall if wall > 0 else 0.0,
            occupancy / max(decode_ticks, 1), self.slots,
        )
        return ServeReport(
            results=sorted(results, key=lambda r: r.uid),
            ticks=tick,
            wall_s=wall,
            tokens_generated=tokens,
            mean_occupancy=occupancy / max(decode_ticks, 1),
        )
