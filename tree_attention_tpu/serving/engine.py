"""Slot-based continuous batching with stall-free chunked prefill.

The lockstep :func:`~tree_attention_tpu.models.decode.generate` decodes one
batch whose rows start, step and stop together — requests with different
prompt lengths, arrival times or stop points cannot share it, so aggregate
tokens/sec dies at real traffic. This engine holds a **fixed batch of S cache
slots** (one :class:`~tree_attention_tpu.models.decode.KVCache` of batch S
with per-slot lengths) plus a request queue, and runs a tick loop:

1. **Admit** — every free slot takes the oldest pending request whose
   arrival time has passed; the slot enters the ``prefilling`` state with
   nothing on the device yet.
2. **Step** — ONE compiled **mixed** step advances the whole batch: every
   live slot contributes its one decode token, and up to ``prefill_budget``
   prompt tokens of the prefilling slots ride along as fixed-size chunks
   (``prefill_chunk``), written **directly into each slot's region of the
   batch cache** at that slot's running offset via the ragged mixed-Tq
   ``forward_step`` (per-slot ``n_tokens``). No B=1 mini cache, no insert
   copy, no per-admit host sync: a long prompt costs each live slot at most
   one chunk of extra latency per tick instead of a whole-prompt stall —
   the Sarathi-style stall-free batching shape (arXiv:2403.02310). Chunk
   sizes come from a small fixed power-of-two bucket set, so occupancy
   changes and chunk mixtures never recompile (pure-decode ticks reuse the
   same program at Tq=1).
3. **Retire** — a slot whose request hit EOS or its token budget frees
   immediately and is refilled on the next admission pass.

The slot lifecycle is ``free -> prefilling -> live -> (EOS | budget) ->
free``. The first sampled token is never fetched on its own: the final
chunk's sample lands in the per-tick batched token fetch that the decode
loop already pays (one host sync per tick, total).

Variants:

- ``admission="whole"`` keeps the legacy blocking path — the whole prompt
  prefills into a prompt-bucket-sized B=1 mini cache and is inserted into
  the slot in one shot. Its first sampled token ALSO rides the per-tick
  fetch (the slot sits out one step while the token parks in the device
  token vector).
- ``quantize=True`` serves from an int8 cache. Chunked admission then runs
  its chunks against ONE preallocated exact **staging** cache (int8 rows
  cannot hold exact prefill activations), and at final-chunk completion the
  staged prefix is masked, quantized under its own frozen per-channel
  scales, and inserted — the quantize-after-prefill contract, per slot.
  One prompt stages at a time; decode ticks never wait for more than a
  chunk of prefill work either way.

- ``prefix_cache=True`` (ISSUE 5) reuses shared prompt prefixes across
  requests: a host-side radix tree over prompt blocks maps to a
  device-resident ref-counted KV block pool
  (:mod:`~tree_attention_tpu.serving.prefix_cache`, RadixAttention,
  arXiv:2312.07104). On admit, the longest cached prefix is copied
  pool -> slot (or pool -> staging under int8) with one jitted donated
  gather and only the unmatched suffix rides the chunk budget; when a
  prompt's prefill completes, its full blocks are published slot -> pool
  with one jitted scatter (int8 publishes the exact staged rows, so a
  later hit re-quantizes under its own frozen scales — the
  quantize-after-prefill rule survives bit-for-bit).

- ``kv_layout="paged"`` (ISSUE 6, the default) replaces the per-slot
  contiguous cache with ONE ref-counted block pool under every slot AND
  the prefix cache (vLLM's PagedAttention, arXiv:2309.06180): each slot
  is a host-side block table into the pool
  (:class:`~tree_attention_tpu.models.decode.PagedKVCache`), physical
  blocks are allocated on demand by a reservation-based host allocator
  (:mod:`~tree_attention_tpu.serving.block_pool`), and prefix reuse is
  **reference-in-place** — a radix hit bumps pins and writes pool ids
  into the slot's table (zero KV bytes moved, vs. the contiguous
  layout's pool→slot gather), while prefill completion publishes by
  HANDING blocks over to the tree. Admissions that cannot reserve their
  worst-case block count simply wait in the queue, so the pool can be
  sized well under ``slots × cache_len`` and the slot count can exceed
  what a contiguous layout could hold at equal bytes. int8 serving pages
  the slot cache with per-BLOCK scale scalars riding the pool (ISSUE 13),
  so quantized blocks publish into and hit from the same radix tree as
  exact ones — the quantize-after-prefill contract holds at block
  granularity, and hits dequant-gather the matched blocks into the
  staging cache. With ``host_blocks > 0`` the pool grows a host-RAM
  demotion tier under it: radix eviction demotes refcount-0 blocks
  (staged D2H, one batched gather per tick) instead of freeing them, and
  a hit on a demoted path restores it with one batched H2D scatter — the
  effective prefix cache becomes host-RAM-sized.
  ``kv_layout="contiguous"`` keeps the PR-5 layout.

- ``speculate=True`` (ISSUE 8) turns every live slot's tick into a
  **draft-and-verify** step (speculative decoding, arXiv:2211.17192): a
  host drafter proposes up to ``draft_k`` candidate tokens from the
  slot's own history (prompt-lookup n-grams by default — zero extra
  model; a token *tree* with ``drafter="ngram-tree"``, verified under
  the tree-attention ancestor mask — SpecInfer, arXiv:2305.09781; or a
  small draft model), the ONE compiled mixed-Tq step scores all of them
  as a prefill-style chunk, the longest accepted root path commits in a
  burst (plus the model's free bonus token at the divergence), and
  rejections roll the slot's length back through the next step's
  ``reset_val`` — with paged blocks past the rollback point unmapped
  back into the slot's reservation so rolled-back KV never leaks pool
  capacity. Greedy only: committed tokens are token-for-token identical
  to non-speculative decode, the hard parity contract
  (``tests/test_serving_spec.py`` pins it across exact/int8 ×
  chunked/whole × device/mesh).

- **Robustness lifecycle** (ISSUE 10): ``serve()`` takes a pre-built
  trace OR a live :class:`RequestSource`; each tick starts with a
  control sweep applying thread-safe mailboxes — :meth:`SlotServer
  .cancel` (client disconnect: retire mid-flight, release prefix pins,
  unmap paged blocks back to the pool — cancellation is cheap by
  construction under the paged layout), per-request deadlines
  (expired-in-queue rejected unserved, expired-in-flight retired with
  outcome ``deadline``), and :meth:`SlotServer.request_drain` (SIGTERM:
  stop admitting, shed the queue, finish in-flight). Every exit arc
  speaks the closed :data:`OUTCOMES` vocabulary
  (``eos|budget|cancelled|deadline|shed|error``), threaded through
  ``serving_requests_total{outcome}``, span args, flight fields, and
  ``ServeReport.outcomes``; :meth:`SlotServer.leak_report` states the
  no-leak invariant the chaos harness asserts. The HTTP front door
  lives in :mod:`~tree_attention_tpu.serving.ingress`.

Works on one device and on a sequence-sharded mesh (the contiguous cache
is seq-sharded and rides the tree merge; the paged pool is replicated —
block offsets cannot stay aligned with a sequence shard — and rides the
flash/Pallas paths).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Set, Tuple, Union,
)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tree_attention_tpu import obs
from tree_attention_tpu.obs.flight import FLIGHT
from tree_attention_tpu.obs.metrics import percentile
from tree_attention_tpu.obs.slo import SLOMonitor
from tree_attention_tpu.models.decode import (
    KVCache,
    PagedKVCache,
    PagedQuantKVCache,
    QuantKVCache,
    compact_decode_window,
    copy_pool_block,
    forward_step,
    gather_kv_blocks,
    init_cache,
    init_paged_cache,
    insert_dequant_prefix,
    paged_insert_slot,
    quantize_cache,
    quantize_paged_blocks,
    sample_rows,
    sample_slots,
    scatter_kv_blocks,
)
from tree_attention_tpu.serving.block_pool import (
    BlockAllocator,
    ShardedBlockAllocator,
)
from tree_attention_tpu.serving.host_pool import HostBlockPool
from tree_attention_tpu.serving.prefix_cache import TIER_DEVICE
from tree_attention_tpu.serving.speculation import (
    Drafter,
    DraftProposal,
    PackedSpec,
    accept_longest_path,
    accept_stochastic_path,
    make_drafter,
    pack_proposal,
    pack_siblings,
)
from tree_attention_tpu.models.transformer import Params, TransformerConfig
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving")

# Serving observability. Occupancy/queue/latency metrics are host-loop
# truths (execution-true, not trace-time): the loop sets/observes them as
# slots change hands; token/request/chunk counters count work the engine
# finished.
_SLOTS_OCCUPIED = obs.gauge(
    "serving_slots_occupied",
    "live slots in the serving batch (set once per tick)",
)
_QUEUE_WAIT = obs.histogram(
    "serving_queue_wait_seconds",
    "wall seconds a request waited between becoming visible and admission",
)
_TOKENS = obs.counter(
    "serving_tokens_total",
    "tokens decoded for live slots by executed serving ticks",
)
_REQUESTS = obs.counter(
    "serving_requests_total",
    "requests the engine finished, by outcome",
    labels=("outcome",),
)
_PREFILL_CHUNKS = obs.counter(
    "serving_prefill_chunks_total",
    "prefill chunks scheduled into serving ticks (fused or staged)",
)
_TTFT = obs.histogram(
    "serving_ttft_seconds",
    "wall seconds from request visibility to its first sampled token",
)
_TBT = obs.histogram(
    "serving_tbt_seconds",
    "wall seconds between consecutive tokens of one live slot "
    "(inter-token latency)",
)
_SPEC_PROPOSED = obs.counter(
    "serving_spec_proposed_total",
    "draft tokens proposed into speculative verify ticks",
)
_SPEC_ACCEPTED = obs.counter(
    "serving_spec_accepted_total",
    "proposed draft tokens the verify pass accepted (bonus tokens — the "
    "model's own next token at the divergence point — are not drafts and "
    "do not count)",
)
_SPEC_ACCEPT_RATIO = obs.gauge(
    "serving_spec_acceptance_ratio",
    "lifetime accepted/proposed draft-token ratio (set per verify tick)",
)
_FORKS = obs.counter(
    "serving_forks_total",
    "copy-on-write forks performed (n>1 siblings, best-of-n branches, "
    "and mid-generation fork(uid) branches)",
)
_FORK_SHARED = obs.counter(
    "serving_fork_blocks_shared_total",
    "full ancestor KV blocks a fork SHARED (radix pins + refcounted "
    "CoW blocks) instead of copying or recomputing them",
)
_TREE_BRANCHES = obs.gauge(
    "serving_tree_branches",
    "live sibling branches decoding as token trees in single slots "
    "(set once per tick; 0 when no tree family is in flight)",
)
_SPEC_ACCEPT_SAMPLES = obs.counter(
    "serving_spec_accept_samples_total",
    "per-row stochastic draws consumed by sampled (temperature > 0) "
    "speculative accept walks — the Leviathan ratio test's coupled "
    "samples; greedy verifies draw nothing and do not count",
)


# The ONE retire-outcome vocabulary (ISSUE 10): every way a request can
# leave the engine, threaded unchanged through
# ``serving_requests_total{outcome}``, the per-request span args, and
# ``ServeReport.outcomes`` — a new exit path must add its name here, not
# stringly-type its way in.
OUTCOME_EOS = "eos"              # sampled the request's eos_id
OUTCOME_BUDGET = "budget"        # hit max_new_tokens
OUTCOME_CANCELLED = "cancelled"  # client cancelled (disconnect) mid-flight
OUTCOME_DEADLINE = "deadline"    # per-request deadline expired
OUTCOME_SHED = "shed"            # dropped unserved (drain / load shedding)
OUTCOME_ERROR = "error"          # live-submitted request failed validation
OUTCOMES = (OUTCOME_EOS, OUTCOME_BUDGET, OUTCOME_CANCELLED,
            OUTCOME_DEADLINE, OUTCOME_SHED, OUTCOME_ERROR)


@dataclasses.dataclass
class Request:
    """One generation request for the serving loop.

    ``arrival_tick`` is synthetic-trace time in decode ticks: the request
    becomes visible to the scheduler once the loop's tick counter reaches
    it (0 = already queued at start). ``eos_id`` stops generation early
    when sampled (the EOS token is included in the output).

    The ingress-facing fields (ISSUE 10) all default off:

    - ``deadline_s`` — absolute ``time.monotonic()`` deadline; expired in
      queue the request is rejected unserved, expired in flight it is
      retired with outcome ``deadline`` (work that can no longer meet its
      SLO is shed, not finished late).
    - ``on_token`` / ``on_finish`` — per-request streaming callbacks,
      invoked ON THE ENGINE THREAD as tokens commit / at retire; they
      must hand off fast (the ingress pushes into per-request queues)
      and never raise (a raising callback is logged and dropped, the
      request keeps running).
    - ``visible_at`` — wall-clock visibility override set by live
      sources at submission, so queue-wait/TTFT clocks start when the
      client's request entered the system, not when the loop first saw
      it.
    """

    uid: int
    prompt: Sequence[int]
    max_new_tokens: int
    arrival_tick: int = 0
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    on_token: Optional[Callable[[int], None]] = None
    on_finish: Optional[Callable[["RequestResult"], None]] = None
    visible_at: Optional[float] = None
    # Sampling (ISSUE 15) — None defers to the engine's defaults.
    # ``seed`` salts the request's PRNG key (default: the uid), so a
    # fixed-seed request resamples bit-identically across serves.
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    seed: Optional[int] = None
    # Copy-on-write forking (ISSUE 15): ``n > 1`` serves n completions
    # of one prompt as one prefill + (n-1) forked siblings sharing every
    # full ancestor KV block; ``best_of = k`` runs k branches and
    # streams only the winner by cumulative logprob (requires n == 1).
    # ``fork_at = j`` self-forks the request after its j-th emitted
    # token (the replayable mid-generation-branch trace knob). Branch
    # events stream through ``on_branch_token(index, tok)`` /
    # ``on_branch_finish(index, result)`` when set; otherwise only
    # branch 0 reaches the legacy ``on_token``/``on_finish``.
    n: int = 1
    best_of: Optional[int] = None
    fork_at: Optional[int] = None
    on_branch_token: Optional[Callable[[int, int], None]] = None
    on_branch_finish: Optional[
        Callable[[int, "RequestResult"], None]] = None
    # Cross-process trace context (ISSUE 16): ``(trace_id, parent
    # span_id)`` adopted from the ingress's W3C-traceparent header (or
    # minted there). Rides the Request object through every hop —
    # router relay, replica ingress, disagg prefill→decode adoption —
    # so one Perfetto load of the merged per-process traces shows the
    # request as one connected flow. ``None`` = untraced (direct
    # engine callers; nothing is emitted or allocated).
    trace: Optional[Tuple[str, str]] = None


@dataclasses.dataclass
class RequestResult:
    uid: int
    tokens: List[int]
    prompt_len: int
    arrival_tick: int
    admit_tick: int  # -1: never admitted (cancelled/expired/shed in queue)
    finish_tick: int
    queue_wait_s: float
    completion_s: float  # visible -> finished, wall seconds
    outcome: str  # one of OUTCOMES
    ttft_s: float = 0.0  # visible -> first sampled token, wall seconds
    # Prompt tokens served from the radix prefix cache at admit (0 = cold
    # or cache off). Exposed so a front-end can report per-request reuse
    # upstream — the fleet router's approximate-tree feedback (ISSUE 11)
    # reads it from the ingress's usage block.
    prefix_hit_tokens: int = 0
    # Fork-family branch index (ISSUE 15): 0 = the parent/only branch; a
    # request with n/best_of > 1 (or mid-generation forks) finishes once
    # per branch, all under the family's one uid.
    index: int = 0
    # Sum of the model log-probabilities of this branch's sampled tokens
    # — best-of-n's server-side selection key. Speculative serving tracks
    # it too (ISSUE 20): each verify row's fused output carries the
    # draw's logprob, so accepted bursts accumulate bit-identically to
    # the non-speculative stream.
    cum_logprob: float = 0.0
    # Finished request-cost ledger (ISSUE 16): the dict
    # ``obs.REQLOG.finish`` returned at retire — wall segments, token
    # and KV-block attribution, trace ids. ``None`` when the ledger is
    # disarmed, and on every branch after the first for n>1 families
    # (the ledger is per-uid, closed once).
    ledger: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class _ForkFamily:
    """Host bookkeeping of one n>1 / best-of-n request (ISSUE 15).

    Admission reserves the whole family atomically: the parent's
    worst-case blocks plus each sibling's worst-case NEW blocks (its
    total minus the full ancestor blocks it will share), and one slot
    per branch (siblings park in state ``fpend`` so prefill never
    deadlocks two half-admitted families against each other). The
    siblings fork the moment the parent's first token lands — before
    its EOS check, so even a one-token parent still yields n
    independent samples — each re-consuming the last prompt token into
    its own copy-on-write tail block and sampling its own first token
    under its own key."""

    req: Request
    parent_slot: int
    sibling_slots: List[int]
    sib_reserve: int       # worst-case NEW blocks per sibling
    hold: int              # unspent family reservation (siblings not yet
    #                        forked; returned on pre-fork retirement)
    best_of: bool
    branches: int
    forked: bool = False
    done: List[RequestResult] = dataclasses.field(default_factory=list)
    # Token-tree sibling decode (ISSUE 20): the family's k branches
    # share ONE slot, replaying their divergent suffixes as one
    # verify-shaped row bundle per tick under tree_mask/positions. The
    # device cache is frozen at ``base_len`` committed rows (the shared
    # ancestor path); each live branch's tokens past ``fork_len - 1``
    # are its private suffix, re-verified every tick. Branch b's j-th
    # token samples under fold_in(fold_in(fold_in(base, salt), b),
    # fork_len + depth) — the fork-slot path's exact key chain, so the
    # two layouts are token-identical under one seed.
    tree: bool = False
    base_len: int = 0      # frozen committed length (shared ancestors)
    fork_len: int = 0      # emitted tokens shared by all branches + 1
    br_tokens: List[List[int]] = dataclasses.field(default_factory=list)
    br_cum_lp: List[float] = dataclasses.field(default_factory=list)
    br_live: List[bool] = dataclasses.field(default_factory=list)
    br_index: List[int] = dataclasses.field(default_factory=list)
    br_ttft: List[float] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServeReport:
    """One serve() run: per-request results plus aggregate accounting."""

    results: List[RequestResult]
    ticks: int
    wall_s: float
    tokens_generated: int
    mean_occupancy: float  # live slots per executed decode tick
    tbt_s: List[float] = dataclasses.field(default_factory=list)
    slo: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Prefix-reuse accounting for THIS run (diff of the pool's lifetime
    # stats over the serve() call); empty when the cache is off.
    prefix: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Paged-pool accounting (block occupancy at run end + peak); empty
    # under the contiguous layout.
    kv: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Speculative-decoding accounting for THIS run (proposed/accepted
    # draft tokens, acceptance_rate, verify ticks); empty when off.
    spec: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Disaggregated prefill/decode accounting (ISSUE 12): handoff counts,
    # queue peak, blocks transferred, kv_bytes_moved (pinned 0 in-process)
    # — empty for a fused engine.
    handoff: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Per-request ledger aggregates for THIS run (ISSUE 16):
    # ``obs.aggregate_ledgers`` over the finished ledgers attached to
    # results — phase-wall sums/p50s, token and KV-block totals. Empty
    # when the request ledger is disarmed.
    requests: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def outcomes(self) -> Dict[str, int]:
        """Retire-outcome counts over the run (the OUTCOMES vocabulary;
        only outcomes that occurred appear)."""
        out: Dict[str, int] = {}
        for r in self.results:
            out[r.outcome] = out.get(r.outcome, 0) + 1
        return {k: out[k] for k in sorted(out)}

    def completion_percentiles(self) -> Dict[str, float]:
        cs = sorted(r.completion_s for r in self.results)
        return {"p50_s": percentile(cs, 0.50), "p95_s": percentile(cs, 0.95)}

    def latency_percentiles(self) -> Dict[str, float]:
        """TTFT (visible -> first token) and inter-token latency (gap
        between consecutive tokens of one slot, pooled over slots) — the
        two serving latencies chunked prefill exists to protect. Requests
        that never produced a token (cancelled/expired/shed unserved)
        have no TTFT and are excluded rather than skewing the
        distribution toward 0."""
        ttft = sorted(r.ttft_s for r in self.results if r.tokens)
        tbt = sorted(self.tbt_s)
        return {
            "ttft_p50_s": percentile(ttft, 0.50),
            "ttft_p95_s": percentile(ttft, 0.95),
            "tbt_p50_s": percentile(tbt, 0.50),
            "tbt_p95_s": percentile(tbt, 0.95),
        }

    def as_dict(self) -> Dict[str, Any]:
        waits = sorted(r.queue_wait_s for r in self.results)
        return {
            "requests": len(self.results),
            "ticks": self.ticks,
            "wall_s": round(self.wall_s, 4),
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_per_sec, 1),
            "mean_occupancy": round(self.mean_occupancy, 2),
            "queue_wait_p50_s": round(waits[len(waits) // 2], 4) if waits else 0.0,
            "outcomes": self.outcomes,
            **{k: round(v, 4) for k, v in self.completion_percentiles().items()},
            **{k: round(v, 5) for k, v in self.latency_percentiles().items()},
            **({"slo": self.slo} if self.slo else {}),
            **({"prefix": self.prefix} if self.prefix else {}),
            **({"kv": self.kv} if self.kv else {}),
            **({"spec": self.spec} if self.spec else {}),
            **({"handoff": self.handoff} if self.handoff else {}),
            **({"request_ledgers": self.requests} if self.requests else {}),
        }


def synthetic_trace(
    n_requests: int,
    *,
    prompt_len: int = 32,
    prompt_jitter: int = 0,
    max_new_tokens: int = 16,
    arrival_every: int = 0,
    vocab_size: int = 256,
    seed: int = 0,
    eos_id: Optional[int] = None,
    prefix_share: float = 0.0,
    prefix_len: int = 0,
    prefix_count: int = 1,
    prefix_seed: Optional[int] = None,
    n: int = 1,
    best_of: int = 0,
    fork_at: int = 0,
) -> List[Request]:
    """A reproducible request trace: random prompts, optional length jitter,
    arrivals every ``arrival_every`` ticks (0 = all queued at start).

    ``prefix_share`` / ``prefix_len`` model production traffic's shared
    system prompts and templates (the workload the prefix cache exists
    for): that fraction of requests draws its first ``prefix_len`` tokens
    from a small fixed set of ``prefix_count`` shared prefixes (round-
    robin) and only the remainder is per-request random. The shared part
    is clamped to ``plen - 1`` so every prompt keeps a unique-able
    suffix token. ``prefix_seed`` draws the SHARED prefixes from their
    own rng stream, so traces with different ``seed`` values (fresh
    per-request randomness) can still share one prefix population — the
    shape a warm-pool steady-state measurement needs; ``None`` keeps
    everything on the one ``seed`` stream.

    ``n`` / ``best_of`` / ``fork_at`` (ISSUE 15) stamp the fork-family
    fields onto every request, so fork workloads replay through the
    same bench and chaos harnesses as everything else: ``n > 1`` makes
    each trace entry an n-completion family, ``best_of > 1`` a
    server-side-selected one, and ``fork_at > 0`` self-forks each
    request after that many emitted tokens (the mid-generation-branch
    chaos shape).
    """
    if not 0.0 <= prefix_share <= 1.0:
        raise ValueError(f"prefix_share must be in [0, 1], "
                         f"got {prefix_share}")
    rng = np.random.default_rng(seed)
    prefix_rng = rng if prefix_seed is None else \
        np.random.default_rng(prefix_seed)
    shared = [
        prefix_rng.integers(0, vocab_size,
                            size=max(prefix_len, 0)).astype(np.int32)
        for _ in range(max(prefix_count, 1))
    ] if prefix_share > 0.0 and prefix_len > 0 else []
    reqs = []
    n_shared = 0
    for i in range(n_requests):
        lo = max(1, prompt_len - prompt_jitter)
        hi = prompt_len + prompt_jitter
        plen = int(rng.integers(lo, hi + 1))
        if shared and rng.random() < prefix_share:
            p = min(prefix_len, plen - 1)
            prompt = np.concatenate([
                shared[n_shared % len(shared)][:p],
                rng.integers(0, vocab_size, size=plen - p).astype(np.int32),
            ])
            n_shared += 1
        else:
            prompt = rng.integers(0, vocab_size, size=plen).astype(np.int32)
        reqs.append(Request(
            uid=i,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            arrival_tick=i * arrival_every,
            eos_id=eos_id,
            n=max(n, 1),
            best_of=best_of if best_of > 1 else None,
            fork_at=fork_at if fork_at > 0 else None,
        ))
    return reqs


class RequestSource:
    """Where the tick loop gets its work (ISSUE 10).

    ``serve()`` used to eat a pre-built request list; a real ingress
    feeds requests as clients produce them. This is the seam: the loop
    calls :meth:`poll` once per tick for newly visible requests,
    :meth:`next_arrival` to fast-forward synthetic time across idle
    gaps, :meth:`wait` to block briefly when a live feeder has nothing
    yet, and :meth:`close` when draining. The base class is an empty,
    already-exhausted source; :class:`StaticRequestSource` wraps the
    legacy list, and the ingress's ``QueueRequestSource``
    (:mod:`~tree_attention_tpu.serving.ingress`) is the thread-safe
    live feeder.
    """

    def poll(self, tick: int) -> List[Request]:
        """Requests that became visible by ``tick`` (each returned
        exactly once)."""
        return []

    def next_arrival(self) -> Optional[int]:
        """The next future arrival tick (synthetic sources only), or
        None when arrivals are wall-clock driven or exhausted."""
        return None

    def wait(self, timeout: float) -> bool:
        """Block up to ``timeout`` seconds for new work (live feeders);
        returns True if work may be available. Synthetic sources return
        False immediately — the loop fast-forwards instead of sleeping."""
        return False

    def close(self) -> None:
        """Stop accepting/producing new requests (graceful drain)."""

    @property
    def exhausted(self) -> bool:
        """True when no request will ever be returned again."""
        return True


class StaticRequestSource(RequestSource):
    """The legacy shape: a fixed trace, visible by ``arrival_tick``."""

    def __init__(self, requests: Sequence[Request]):
        self._reqs = sorted(requests,
                            key=lambda r: (r.arrival_tick, r.uid))
        self._pos = 0

    def poll(self, tick: int) -> List[Request]:
        out: List[Request] = []
        while (self._pos < len(self._reqs)
               and self._reqs[self._pos].arrival_tick <= tick):
            out.append(self._reqs[self._pos])
            self._pos += 1
        return out

    def next_arrival(self) -> Optional[int]:
        if self._pos >= len(self._reqs):
            return None
        return self._reqs[self._pos].arrival_tick

    def close(self) -> None:
        self._pos = len(self._reqs)  # drop the rest: nothing more arrives

    @property
    def exhausted(self) -> bool:
        return self._pos >= len(self._reqs)


def _bucket(n: int, cap: int, floor: int = 8, multiple: int = 1) -> int:
    """Pad a prompt length up to a power-of-two bucket (bounded compiles:
    one prefill program per bucket, not per distinct prompt length),
    rounded to ``multiple`` (a seq-sharded mini cache must divide over the
    mesh) and capped at ``cap``."""
    b = floor
    while b < n:
        b *= 2
    b = -(-b // max(multiple, 1)) * max(multiple, 1)
    return min(b, cap)


class SlotServer:
    """Continuous-batching engine: S slots, a queue, one compiled mixed step.

    Args:
      params / cfg: the model served.
      slots: batch size S of the slot cache — the max concurrent requests.
      cache_len: per-slot KV capacity; every admitted request needs
        ``prompt_len + max_new_tokens <= cache_len``.
      mesh (+ axis names): sequence-shard the slot cache over a mesh; the
        ragged decode step runs the tree merge per tick.
      quantize: serve from an int8 cache — each request prefills exactly
        (staged, under chunked admission) then quantizes that slot's rows
        under its own frozen per-channel scales (the quantize-after-prefill
        contract, per slot).
      quant_kernel: which q8 kernel decode ticks run (``"q8q"`` / ``"q8"``).
      temperature / seed: sampling (0 = greedy, the deterministic default).
      prefill_chunk: max prompt tokens one tick may write for one slot
        (clamped to ``cache_len``). Smaller = lower inter-token latency
        spikes for live slots, more ticks per prompt.
      prefill_budget: max TOTAL prompt tokens per tick across prefilling
        slots — the Sarathi-style token budget; live decode tokens always
        ride for free. Default: ``slots * prefill_chunk`` (every
        prefilling slot advances one chunk per tick). The padded mixed
        program computes ``S x Tq`` rows whether one chunk rides or all
        of them, so concurrent chunks cost no extra compute; a smaller
        budget only bounds KV-write traffic per tick.
      admission: ``"chunked"`` (default — stall-free, fused into the tick)
        or ``"whole"`` (legacy blocking whole-prompt prefill + insert).
      slo_ttft / slo_tbt / slo_window: the sliding-window SLO monitor's
        targets (seconds) and sample window — a retired request counts
        toward goodput iff its TTFT and worst inter-token gap both met
        the target. The monitor always feeds ``ServeReport.slo``; its
        gauges only publish while the metrics registry records.
      prefix_cache: enable shared-prompt KV reuse — admissions match
        their prompt against a radix tree of published prefixes and skip
        prefill for the matched blocks (reference-in-place under the
        paged layout — zero KV bytes moved, int8 included since
        per-block scales made its blocks shareable (ISSUE 13); one pool
        gather under the contiguous layout, whose int8 per-slot frozen
        scales keep the exact sidecar pool).
      prefix_block: tokens per prefix pool block (power of two; the
        match/publish granularity). Under the paged layout this is also
        the default page size (``kv_block``) so matching stays
        block-aligned with the tables.
      prefix_pool_blocks: how many blocks the prefix tree may RETAIN
        (LRU-evicted at refcount 0). Under the contiguous layout this
        sizes the separate device pool (default 64); under the paged
        layout it is only a retention cap on the shared pool (default
        None = bounded by the pool itself). The CLI's
        ``--prefix-pool-blocks`` is deprecated in favor of the unified
        ``--kv-blocks`` budget.
      kv_layout: ``"paged"`` (default — one block pool under every slot,
        block-table decode, copy-free prefix hits) or ``"contiguous"``
        (the PR-5 layout: per-slot contiguous regions + gather hits).
      kv_block: tokens per pool block (power of two). Default: follows
        ``prefix_block`` when the prefix cache is on (match granularity
        == page size), else 64. On a real TPU keep it >= the dtype's
        minimum sublane tile (8 f32 / 16 bf16 / 32 int8).
      kv_blocks: TOTAL pool capacity in blocks — the one KV memory
        budget (slots and prefix cache share it). Default:
        ``slots × ceil(cache_len / kv_block)``, the contiguous layout's
        capacity at equal bytes. Size it smaller to over-subscribe:
        admissions whose worst case cannot be reserved wait in the
        queue, and a request that could never fit fails validation with
        a clear message.
      speculate: draft-and-verify speculative decoding (arXiv:2211.17192)
        on the mixed-Tq tick. Every live slot's tick becomes a verify
        chunk: a host drafter proposes up to ``draft_k`` tokens, the ONE
        compiled step scores them all (prefill-style), the longest
        accepted path commits at once and rejections roll the slot's
        device length back (paged blocks past the rollback unmap without
        leaking pool capacity). Greedy only (``temperature`` must be 0 —
        the accept rule is exact there): committed tokens are
        token-for-token identical to non-speculative decode.
      draft_k: max draft tokens per slot per verify tick (1..31 — the
        tree mask packs into int32 bitmasks). One verify commits between
        1 and ``draft_k + 1`` tokens.
      drafter: ``"ngram"`` (default — prompt-lookup over the slot's own
        history, zero extra model), ``"ngram-tree"`` (multi-branch token
        trees verified under the tree-attention mask, SpecInfer
        arXiv:2305.09781), or any :class:`~tree_attention_tpu.serving
        .speculation.Drafter` instance (e.g. ``DraftModelDrafter``).
        Tree proposals fall back to their root-path chain on the one
        topology without mask plumbing (contiguous layout on a >1-way
        seq mesh).
      block_pool: bring-your-own :class:`BlockAllocator` (disaggregated
        serving, ISSUE 12: two engines — a prefill worker and a decode
        worker — share ONE pool ledger so a finished prefill's blocks
        hand over by pure ownership transfer). Paged layout only;
        ``kv_blocks`` defaults to (and must equal) the pool's capacity.
        The DEVICE pool arrays are shared by the orchestrator
        (:class:`~tree_attention_tpu.serving.disagg.DisaggServer`
        rebinds both caches to one array set and relays after every
        dispatch); this engine still allocates its own transient
        initial arrays, which the rebind immediately frees.
      prefix_index: bring-your-own
        :class:`~tree_attention_tpu.serving.prefix_cache
        .PagedPrefixIndex` over ``block_pool`` (the disaggregated pair
        shares one radix tree: the prefill worker matches/adopts, the
        decode worker holds the request's pins until retire). Implies
        the prefix cache is on; paged serving only (int8 included since
        per-block scales made int8 blocks shareable, ISSUE 13), and the
        index's block size must equal ``kv_block``.
      host_blocks: KV tiering (ISSUE 13) — capacity of the host-RAM
        demotion tier in blocks (``--host-blocks``; 0 = off). Radix
        eviction then DEMOTES refcount-0 blocks into pinned host memory
        (async D2H staged off the tick, one jitted gather per batch)
        instead of freeing them, and a prefix hit on a demoted path
        restores it with one batched H2D scatter into freshly allocated
        device blocks — the effective prefix cache becomes
        host-RAM-sized. Requires the paged layout and the prefix cache
        (demotion IS radix eviction).
    """

    def __init__(
        self,
        params: Params,
        cfg: TransformerConfig,
        *,
        slots: int,
        cache_len: int,
        mesh: Optional[Mesh] = None,
        quantize: bool = False,
        quant_kernel: str = "q8q",
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        prefill_chunk: int = 256,
        prefill_budget: Optional[int] = None,
        admission: str = "chunked",
        slo_ttft: float = 1.0,
        slo_tbt: float = 0.2,
        slo_window: int = 1024,
        prefix_cache: bool = False,
        prefix_block: int = 64,
        prefix_pool_blocks: Optional[int] = None,
        kv_layout: str = "paged",
        kv_block: Optional[int] = None,
        kv_blocks: Optional[int] = None,
        kv_shard: str = "replicated",
        speculate: bool = False,
        draft_k: int = 4,
        drafter: Union[str, Drafter, None] = None,
        block_pool: Optional[BlockAllocator] = None,
        prefix_index: Optional[Any] = None,
        host_blocks: int = 0,
        tree_sampling: bool = True,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if admission not in ("chunked", "whole"):
            raise ValueError(
                f"admission must be 'chunked' or 'whole', got {admission!r}"
            )
        if kv_layout not in ("paged", "contiguous"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', "
                f"got {kv_layout!r}"
            )
        if kv_shard not in ("replicated", "seq"):
            raise ValueError(
                f"kv_shard must be 'replicated' or 'seq', got {kv_shard!r}"
            )
        if kv_shard == "seq" and kv_layout != "paged":
            raise ValueError(
                "kv_shard='seq' shards the paged block pool; the "
                "contiguous layout already shards the token axis via "
                "the mesh"
            )
        if block_pool is not None and kv_layout != "paged":
            raise ValueError(
                "block_pool sharing requires kv_layout='paged' (the "
                "contiguous layout has no block ledger to share)"
            )
        if host_blocks < 0:
            raise ValueError(f"host_blocks must be >= 0, got {host_blocks}")
        if host_blocks and kv_layout != "paged":
            raise ValueError(
                "host_blocks KV tiering requires kv_layout='paged' (the "
                "tier demotes pool blocks; the contiguous layout has "
                "none)"
            )
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        if prefill_budget is not None and prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {prefill_budget}"
            )
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.cache_len = cache_len
        self.mesh = mesh
        self.quantize = quantize
        self.quant_kernel = quant_kernel
        self.temperature = float(temperature)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        self.top_k = int(top_k)
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = off)")
        self._speculate = bool(speculate)
        if self._speculate:
            # Sampled acceptance (temperature > 0) runs the Leviathan
            # ratio test (arXiv:2211.17192) specialised to point-mass
            # drafts: each verify row draws from the model's own
            # distribution under the request's fold_in(key, j) stream
            # key and accepts the draft iff the draw reproduces it —
            # distribution-exact AND token-identical to the non-spec
            # sampled path under the same seed. Temperature 0 keeps the
            # legacy greedy accept rule bit-for-bit (ISSUE 20).
            if not 1 <= draft_k <= 31:
                raise ValueError(
                    f"draft_k must be in [1, 31] (int32 tree bitmasks), "
                    f"got {draft_k}"
                )
        self.draft_k = draft_k
        self.admission = admission
        self.prefill_chunk = min(prefill_chunk, cache_len)
        self.prefill_budget = (
            slots * self.prefill_chunk if prefill_budget is None
            else prefill_budget
        )
        # Per-slot sampling state (ISSUE 15). Each slot's PRNG key is
        # its REQUEST's key (fold_in(base, seed-or-uid) then the branch
        # index); the j-th emitted token folds j in — see
        # models.decode.sample_slots for the reproducibility contract.
        # The host mirrors (_temp_np/_topk_np) ride every dispatch as
        # plain operands, so per-request sampling params never recompile.
        self._base_key = jax.random.PRNGKey(seed)
        self._keys = jnp.zeros((slots, 2), jnp.uint32)
        self._lp = jnp.zeros((slots,), jnp.float32)
        self._lp_host = np.zeros((slots,), np.float32)
        self._temp_np = np.zeros((slots,), np.float32)
        self._topk_np = np.zeros((slots,), np.int32)
        # Host mirror of each slot's PRNG salt (seed-or-uid): tree
        # sibling rows re-derive the full fold chain IN-PROGRAM from
        # (salt, branch, stream index) operands, so the verify step
        # needs the raw salt, not just the installed per-slot key.
        self._salt_np = np.zeros((slots,), np.int32)
        self._slot_index = [0] * slots
        self._slot_cum_lp = [0.0] * slots
        self._seed_key = jax.jit(self._seed_key_fn, donate_argnums=(0,))
        # Copy-on-write fork state (ISSUE 15): live fork families by
        # uid, per-slot refcount-shared block sets (released — not
        # freed — on retire; the last owner's release frees), pending
        # device-length resets for freshly forked live slots, the
        # fork(uid) mailbox's deferral carry, and per-tick flight
        # counters.
        self._families: Dict[int, _ForkFamily] = {}
        # Token-tree sibling families by SLOT (ISSUE 20): the n>1 /
        # best-of families whose branches decode as one packed token
        # tree in a single slot instead of n forked slots. Every fam
        # here is also in _families (the join/best-of machinery is
        # shared); the per-tick counters feed the flight recorder.
        self._tree_fams: Dict[int, _ForkFamily] = {}
        self._tree_sampling = bool(tree_sampling)
        self._tick_tree_branches = 0
        self._tick_branch_retired = 0
        self._slot_shared: List[set] = [set() for _ in range(slots)]
        self._live_reset: Dict[int, int] = {}
        self._fork_uids: List[int] = []
        self._fork_carry: Dict[int, int] = {}
        self._uid_next_index: Dict[int, int] = {}
        self._forks_life = 0
        self._fork_shared_life = 0
        self._tick_forks = 0
        self._tick_fork_shared = 0
        self._fork_copy = jax.jit(self._fork_copy_fn, donate_argnums=(0,))
        self._sibling_first = jax.jit(self._sibling_first_fn,
                                      donate_argnums=(0, 1))
        self._tree_first = jax.jit(self._tree_first_fn)
        self._tree_branches_life = 0
        self._tree_fams_life = 0
        # Per-slot stash of the prompt-end logits row (device, (V,)) —
        # kept only while the slot's fork family is waiting to expand.
        self._slot_logits: List[Optional[Any]] = [None] * slots

        kw = {"mesh": mesh} if mesh is not None else {}
        self._fs_kw = dict(kw)
        if kv_shard == "seq":
            # Only the batched per-tick steps run on the sharded pool;
            # the B=1 prefill/staging programs below use CONTIGUOUS
            # mini-caches and must not see the flag.
            self._fs_kw["kv_shard"] = "seq"
        # B=1 programs (the legacy mini-cache prefill and the quantized
        # staging cache) cannot shard over a data axis (1 does not divide
        # it) — and need no data parallelism anyway; the batched per-tick
        # step keeps the full mesh spec.
        self._prefill_kw = (
            dict(kw, data_axis=None) if mesh is not None else {}
        )
        self._seq_shards = 1
        if mesh is not None:
            from tree_attention_tpu.parallel.mesh import AXIS_SEQ

            self._seq_shards = max(mesh.shape.get(AXIS_SEQ, 1), 1)
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        # Sequence-sharded pool (ISSUE 18): per-device pool bytes drop to
        # 1/W; the allocator range-partitions global block ids over the
        # mesh's seq shards and decode attention runs the shard_map'd
        # 3-collective tree merge. Host bookkeeping (tables, radix keys,
        # private/shared sets) stays in GLOBAL ids throughout — the shard
        # rebase happens only inside the device-side shard_map bodies.
        self.kv_shard = kv_shard
        self._kv_seq_sharded = kv_shard == "seq" and self._seq_shards > 1
        # Bytes a contiguous-layout hit gathers per matched token — the
        # cost a paged hit deletes (the bytes_moved span arg).
        self._kv_token_bytes = (
            2 * cfg.n_layers * cfg.n_kv_heads * cfg.d_head
            * jnp.dtype(cfg.dtype).itemsize
        )
        # int8 pool bytes per token — what an int8 paged hit's dequant
        # gather into staging actually moves (ISSUE 13).
        self._kv_token_bytes_q = 2 * cfg.n_layers * cfg.n_kv_heads \
            * cfg.d_head
        if self._paged:
            if kv_block is None:
                # Matching granularity == page size keeps radix hits
                # table-aligned (a matched prefix IS whole table entries).
                kv_block = prefix_block if prefix_cache else 64
            elif prefix_cache and kv_block != prefix_block:
                # Honoring only one of them silently would make the
                # recorded config contradict the running granularity.
                raise ValueError(
                    f"paged layout: prefix_block ({prefix_block}) must "
                    f"equal kv_block ({kv_block}) — radix matching "
                    f"happens at page granularity (pass one of them, or "
                    f"equal values)"
                )
            self.kv_block = kv_block
            self._npb = -(-cache_len // kv_block)  # table width (blocks)
            if block_pool is not None:
                # Shared-pool mode (disaggregation): the allocator is the
                # ONE ledger both workers admit/retire against, so this
                # engine's view of capacity must be the pool's — a
                # different kv_blocks would let _validate accept requests
                # the shared pool can never hold (or reject ones it can).
                if kv_blocks is not None and kv_blocks != block_pool.blocks:
                    raise ValueError(
                        f"kv_blocks {kv_blocks} contradicts the shared "
                        f"block_pool's capacity {block_pool.blocks}"
                    )
                if kv_shard == "seq" and (
                    not isinstance(block_pool, ShardedBlockAllocator)
                    or block_pool.shards != self._seq_shards
                ):
                    # Both workers' device pools must agree on the id →
                    # shard placement rule, and the shared ledger is
                    # where that rule lives.
                    raise ValueError(
                        "kv_shard='seq' with a shared block_pool needs a "
                        f"ShardedBlockAllocator over {self._seq_shards} "
                        "shards (one placement rule for every worker)"
                    )
                self.kv_blocks = block_pool.blocks
                self._pool = block_pool
            else:
                self.kv_blocks = (
                    slots * self._npb if kv_blocks is None else kv_blocks
                )
                if kv_shard == "seq":
                    # Round UP to a whole number of per-shard slices: the
                    # device pool and the ledger must split evenly, and
                    # extra blocks only ever ADD capacity.
                    w = self._seq_shards
                    self.kv_blocks = -(-self.kv_blocks // w) * w
                    self._pool = ShardedBlockAllocator(self.kv_blocks, w)
                else:
                    self._pool = BlockAllocator(self.kv_blocks)
            # KV tiering (ISSUE 13): the host-RAM demotion tier under
            # the device pool. Created here (the prefix index attaches
            # to it below); the allocator's flusher hook lets a dry
            # reservation force the staged D2H batch mid-tick, but the
            # steady-state flush point is the end of the tick loop.
            self.host_blocks = host_blocks
            self._host_pool: Optional[HostBlockPool] = None
            self._tick_restored = 0
            if host_blocks:
                if prefix_index is not None:
                    raise ValueError(
                        "host_blocks tiering with a shared prefix_index: "
                        "build the index with its own host_pool instead "
                        "(the tier belongs to the shared tree, not one "
                        "engine)"
                    )
                if not prefix_cache:
                    raise ValueError(
                        "host_blocks KV tiering requires prefix_cache=True "
                        "(demotion is what radix eviction becomes; with "
                        "no radix tree nothing ever demotes)"
                    )
                self.attach_host_tier(HostBlockPool(
                    host_blocks,
                    n_layers=cfg.n_layers,
                    n_kv_heads=cfg.n_kv_heads,
                    block=kv_block,
                    d_head=cfg.d_head,
                    dtype=np.int8 if quantize else np.dtype(
                        jnp.dtype(cfg.dtype).name),
                    quantized=quantize,
                ))
            self._host_table = np.zeros((slots, self._npb), np.int32)
            self._table_dirty = False  # device table starts all-zero too
            self._slot_nblocks = [0] * slots
            self._slot_private: List[set] = [set() for _ in range(slots)]
            self._slot_reserve = [0] * slots
            self._peak_blocks_used = 0
            self._defer_gen = -1  # see the admit loop's generation latch
            cache: Union[KVCache, QuantKVCache, PagedKVCache,
                         PagedQuantKVCache] = init_paged_cache(
                cfg, slots, cache_len, self.kv_blocks,
                block=kv_block, quantize=quantize, kv_shard=kv_shard, **kw
            )
        else:
            self.host_blocks = 0
            self._host_pool = None
            self._tick_restored = 0
            cache = init_cache(cfg, slots, cache_len, **kw)
            if quantize:
                cache = quantize_cache(cache)  # empty -> fallback scales
        self.cache = cache
        self.tok = jnp.zeros((slots,), jnp.int32)

        # Host mirror of slot state (the scheduler's view; device state is
        # the cache + the token vector the mixed step carries). States:
        # "free", "prefill" (chunks in flight), "await" (first sampled
        # token parked in the device token vector until this tick's
        # batched fetch), "live" (decoding).
        self._slot_req: List[Optional[Request]] = [None] * slots
        self._slot_tokens: List[List[int]] = [[] for _ in range(slots)]
        self._slot_admit: List[Tuple[int, float]] = [(0, 0.0)] * slots
        self._slot_state: List[str] = ["free"] * slots
        self._slot_ttft: List[float] = [0.0] * slots
        self._slot_prefix_hit: List[int] = [0] * slots
        self._prefill_pos: List[int] = [0] * slots
        # Where each slot's prefill STARTED (0 cold, the matched length on
        # a prefix hit) — the first consumed chunk resets the slot's
        # device length to exactly this value (a no-op where a contiguous
        # gather already set it; load-bearing under the paged layout,
        # where a hit is pure host bookkeeping).
        self._prefill_start: List[int] = [0] * slots
        self._prompt_np: List[Optional[np.ndarray]] = [None] * slots
        self._prefill_fifo: List[int] = []  # prefilling slots, admit order
        self._last_tok_t: List[float] = [0.0] * slots
        self._slot_wait: List[float] = [0.0] * slots
        self._tok_host = np.zeros((slots,), np.int32)

        # Thread-safe control mailboxes (ISSUE 10): ingress handler
        # threads only ever touch these two under the control lock —
        # cancel() records a uid, request_drain() raises the flag — and
        # the tick loop sweeps both at tick start, so every actual
        # engine/state mutation stays on the loop thread.
        self._ctl_lock = threading.Lock()
        self._cancel_uids: Set[int] = set()
        self._draining = False
        # Per-tick robustness accounting for the flight recorder.
        self._tick_cancelled = 0
        self._tick_deadline = 0
        self._tick_shed = 0

        # Observability plane (PR 4): a per-request span held open from
        # admit to retire (None while the slot is free / tracing is off),
        # the slot's worst inter-token gap (the SLO verdict's TBT side),
        # and its chunk ordinal (the "chunk k/N" trace tag). The SLO
        # monitor itself always runs — it feeds ServeReport.slo — but its
        # gauges only publish while the registry records.
        self._slot_span: List[Optional[Any]] = [None] * slots
        self._slot_max_tbt: List[float] = [0.0] * slots
        self._chunk_k: List[int] = [0] * slots
        self.slo = SLOMonitor(
            ttft_slo=slo_ttft, tbt_slo=slo_tbt, window=slo_window
        )

        # Prefix reuse (ISSUE 5/6): the radix tree, plus the per-slot ref
        # ledger — nodes a slot matched or published stay pinned
        # (unevictable) until that slot retires. Paged serving — int8
        # included, since per-block scales ride the pool (ISSUE 13) —
        # uses the in-place index over the unified pool (zero-copy
        # hits); the contiguous layout keeps the PR-5 gather pool.
        self._prefix: Optional[Any] = None
        self._paged_prefix = False
        self._slot_nodes: List[List[Any]] = [[] for _ in range(slots)]
        self._tick_prefix_hits = 0
        self._tick_prefix_reused = 0
        self._hit_bytes_moved = 0
        if prefix_index is not None:
            # Shared-radix mode (disaggregation): both workers hold pins
            # in ONE tree — the prefill worker matches and adopts, the
            # decode worker inherits the request's pins at handoff and
            # releases them at retire. Any paged index can be shared —
            # int8 included, since per-block scales ride the shared pool
            # (ISSUE 13) — but the contiguous gather pool owns its own
            # device buffers and cannot.
            if not self._paged:
                raise ValueError(
                    "prefix_index sharing requires paged serving "
                    "(kv_layout='paged')"
                )
            if block_pool is None or prefix_index.alloc is not block_pool:
                raise ValueError(
                    "prefix_index must be built over the same shared "
                    "block_pool (one ledger, one tree)"
                )
            if prefix_index.block != self.kv_block:
                raise ValueError(
                    f"prefix_index block {prefix_index.block} must equal "
                    f"kv_block {self.kv_block} (radix matching happens at "
                    f"page granularity)"
                )
            self._prefix = prefix_index
            self._paged_prefix = True
        elif prefix_cache:
            if prefix_block > cache_len:
                # Checked before the pool allocates: a block wider than a
                # slot could never be copied anywhere.
                raise ValueError(
                    f"prefix_block {prefix_block} exceeds cache_len "
                    f"{cache_len}"
                )
            if self._paged:
                # The in-place index serves int8 too (ISSUE 13): blocks
                # carry per-BLOCK scales in the pool, so a published
                # int8 block is self-contained and shareable — the PR-5
                # exact sidecar pool survives only for the contiguous
                # layout.
                from tree_attention_tpu.serving.prefix_cache import (
                    PagedPrefixIndex,
                )

                self._prefix = PagedPrefixIndex(
                    block=self.kv_block, alloc=self._pool,
                    max_cached=prefix_pool_blocks,
                    host_pool=self._host_pool,
                )
                self._paged_prefix = True
            else:
                from tree_attention_tpu.serving.prefix_cache import (
                    PrefixCache,
                )

                self._prefix = PrefixCache(
                    cfg, block=prefix_block,
                    blocks=(64 if prefix_pool_blocks is None
                            else prefix_pool_blocks),
                    mesh=mesh,
                )

        # Reusable host scratch for the legacy whole-prompt admission's
        # padded prompt matrix, one per bucket — the chunked path never
        # allocates per admit, and neither should this one.
        self._whole_scratch: Dict[int, np.ndarray] = {}

        # Quantized + chunked admission stages the exact prefill in ONE
        # preallocated B=1 cache (int8 slots cannot hold exact chunk
        # activations; allocating per admit is the cost this engine
        # removes). One prompt stages at a time. With the prefix cache on,
        # WHOLE int8 admission routes through the same staging cache too
        # (the pool stores exact rows; hits land in staging and the
        # publish reads exact staged rows back out), so it is allocated
        # for that combination as well.
        self._staged_prefill = quantize and admission == "chunked"
        self._needs_staging = quantize and (
            admission == "chunked" or self._prefix is not None
        )
        if self._needs_staging:
            self._staging: KVCache = init_cache(
                cfg, 1, cache_len, **self._prefill_kw
            )
            if self._paged_prefix:
                # int8 paged hits (ISSUE 13): the slot references the
                # matched int8 blocks in place, but the suffix's exact
                # staged prefill needs the prefix as activations-grade
                # rows — ONE jitted dequant gather per hit.
                self._dequant_hit = jax.jit(
                    insert_dequant_prefix, donate_argnums=(0,)
                )

        # jax.jit caches one executable per Tq bucket for the mixed step
        # (pure-decode ticks are the Tq=1 bucket, chunk ticks one of a
        # small power-of-two set) and per prompt bucket for the legacy
        # prefill — bounded compiles for every occupancy/chunk mixture.
        # The jit caches are per INSTANCE (bound methods), so a fresh
        # server recompiles — bench/serving.py warms the same server it
        # times. The tick loop reassigns self.cache/self.tok from each
        # call's outputs, so the old buffers are donated — each call
        # updates the (L,S,Hkv,Tmax,D) cache in place instead of copying
        # it (backends without donation just copy).
        self._mixed = jax.jit(self._mixed_fn, donate_argnums=(6,))
        self._prefill = jax.jit(self._prefill_fn)
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0, 1, 2))
        if self._needs_staging:
            self._stage_chunk = jax.jit(
                self._stage_chunk_fn, donate_argnums=(3,)
            )
            self._stage_final = jax.jit(
                self._stage_final_fn, donate_argnums=(3, 4, 5, 6)
            )
        if self._prefix is not None:
            # Whole-admission prefix hits prefill only the suffix — device-
            # built single-slot chunks through the SAME mixed-step family
            # (every other slot rides inert with its parked token intact).
            self._whole_suffix = jax.jit(
                self._whole_suffix_fn, donate_argnums=(7,)
            )

        # Speculative decoding (ISSUE 8): the host drafter, the per-slot
        # committed-length ledger (the rollback truth — the device length
        # over-counts by the rejected rows until the next step's reset),
        # and the verify-step programs. The tree program only exists where
        # the mask is plumbed; the one unplumbed topology (contiguous
        # cache on a >1-way seq mesh rides the tree merge) falls back to
        # root-path chains, which are exactly causal.
        self._drafter: Optional[Drafter] = None
        # Tree masks need a mask-plumbed attention path: the contiguous
        # tree merge has none, and the paged-QUANT off-kernel path runs
        # its dequantized view through the same merge under a seq mesh
        # (ISSUE 13) — both fall back to root-path chains there.
        self._tree_ok = not (
            self._seq_shards > 1
            and (kv_layout == "contiguous" or quantize
                 or kv_shard == "seq")
        )
        # Verify chunks ride power-of-two Tq buckets like prefill chunks;
        # the bucket must fit the cache's write window, so the draft size
        # clamps to the largest power of two <= min(32, cache_len).
        cap = 1
        while cap * 2 <= min(32, cache_len):
            cap *= 2
        self._spec_rows_cap = cap
        self._slot_clen = [0] * slots
        # Per-slot token history for the drafter, filled INCREMENTALLY
        # (admit writes the prompt, every commit appends its burst) — a
        # per-tick concatenate of prompt + emitted would make host-side
        # drafting O(n^2) over a generation. history = buf[i, :len].
        self._hist_buf = np.zeros((slots, cache_len + 1), np.int32)
        self._hist_len = [0] * slots
        self._spec_proposed = 0   # lifetime draft tokens proposed
        self._spec_accepted = 0   # lifetime draft tokens accepted
        self._spec_ticks = 0      # ticks that verified >= 1 draft token
        self._spec_verifies = 0   # per-SLOT verify events with >= 1 draft
        self._tick_spec: Tuple[int, int, int] = (0, 0, 0)
        if self._speculate:
            self._drafter = (
                make_drafter(drafter or "ngram")
                if isinstance(drafter, str) or drafter is None else drafter
            )
        # The verify-shaped programs serve BOTH speculation and token-
        # tree sibling decode (ISSUE 20) — jitted unconditionally; an
        # engine that never runs a verify tick never compiles them.
        self._spec_lin = jax.jit(
            self._spec_lin_fn, donate_argnums=(8,)
        )
        self._spec_tree = jax.jit(
            self._spec_tree_fn, donate_argnums=(10,)
        )
        self._compact = jax.jit(self._compact_fn, donate_argnums=(0,))

    # -- compiled pieces --------------------------------------------------

    def _seed_key_fn(self, keys, slot, salt, branch):
        """Install slot ``slot``'s request key: fold the request's salt
        (its ``seed`` or uid) and the fork-branch index into the
        engine's base key. Pure function of (engine seed, salt, branch)
        — the reproducibility root: re-serving the same trace re-derives
        the same keys, and every forked sibling gets its own stream."""
        k = jax.random.fold_in(jax.random.fold_in(self._base_key, salt),
                               branch)
        return keys.at[slot].set(k)

    def _fork_copy_fn(self, cache, tok_vec, src, dst, slot, tip):
        """The fork's ONE device dispatch: copy-on-write the partial
        tail block ``src`` into the child's fresh block ``dst`` (a
        no-op self-copy when the fork point is block-aligned and no
        tail exists — ``src == dst == 0``) and park the child's tip
        token in the device token vector (the pure-decode tick reads
        tokens from there). Everything else about a fork is host
        bookkeeping: table row, refcounts, pins."""
        cache = copy_pool_block(cache, src, dst)
        tok_vec = lax.dynamic_update_index_in_dim(tok_vec, tip, slot,
                                                  axis=0)
        return cache, tok_vec

    def _sample_emit(self, last, keys, temp, topk, idx):
        """The ONE per-slot sampling call every emitting program shares
        (models.decode.sample_slots): argmax where the slot's
        temperature is 0 — value-identical to the legacy greedy path —
        temperature/top-k categorical under fold_in(key, idx)
        otherwise. Returns (tokens, model logprobs of the choices)."""
        return sample_slots(last, temp, topk, keys, idx)

    def _chunk_bucket(self, n: int) -> int:
        """Tq bucket for a chunk of ``n`` prompt tokens: power-of-two with
        a floor of 8, capped at ``prefill_chunk`` — the small fixed set of
        mixed-step programs."""
        b = min(8, self.prefill_chunk)
        while b < n:
            b *= 2
        return min(b, self.prefill_chunk)

    def _mixed_fn(self, params, tokens, n_tok, reset, reset_val, emit,
                  cache, keys, temp, topk, idx, lp_vec):
        """THE per-tick program: one mixed-Tq forward_step for every slot.

        ``tokens`` is ``(S, Tq)`` (Tq = 1 on pure-decode ticks, a chunk
        bucket otherwise); slot ``i`` consumes ``n_tok[i]`` rows — 1 for a
        live decode slot, a chunk for a prefilling slot, 0 for everything
        else (inert: nothing written, length frozen). ``reset`` sets a
        slot's length to ``reset_val[i]`` before the write — 0 for a cold
        first chunk (the slot reuses a retired slot's region), the
        matched prefix length on a prefix hit (where a contiguous gather
        already set the device length this is a no-op; under the paged
        layout the hit was pure host bookkeeping and THIS is where the
        device learns it). Each slot samples from its own last valid row
        under its own key/temperature/top-k (``keys``/``temp``/``topk``/
        ``idx`` — ISSUE 15; temperature-0 slots are exact argmax);
        ``emit`` keeps the sample (decode slots and final-chunk slots)
        or holds the slot's row-0 token AND its parked logprob
        (everything else — in particular a parked first token rides
        through unchanged). Returns the token vector, the logprob
        vector, ONE fused ``(S, 2)`` int32 fetch vehicle (tokens +
        bitcast logprobs — the per-tick host sync stays a single
        array), and the cache.
        """
        length = jnp.where(reset, reset_val, cache.length)
        cache = dataclasses.replace(cache, length=length)
        kw = dict(self._fs_kw)
        if self.quantize:
            kw["quant_kernel"] = self.quant_kernel
        logits, new_cache = forward_step(
            params, tokens, cache, self.cfg, n_tokens=n_tok, **kw
        )
        row = jnp.maximum(n_tok - 1, 0)
        last = jnp.take_along_axis(logits, row[:, None, None], axis=1)[:, 0]
        tok_s, lp_s = self._sample_emit(last, keys, temp, topk, idx)
        nxt = jnp.where(emit, tok_s, tokens[:, 0])
        lp_out = jnp.where(emit, lp_s, lp_vec)
        fused = jnp.concatenate(
            [nxt[:, None],
             lax.bitcast_convert_type(lp_out, jnp.int32)[:, None]],
            axis=1,
        )
        # ``last`` rides out as a device carry: a fork family samples
        # its siblings' first tokens from the PARENT's exact prompt-end
        # logits row (bit-identical to the parent's own sample point —
        # the greedy parity gate's exactness), never re-computing a
        # written KV row. Fetched never, read only at fork time.
        return nxt, lp_out, fused, last, new_cache

    def _whole_suffix_fn(self, params, rows, slot, n, last, first, start,
                         cache, tok_vec, keys, temp, topk, idx, lp_vec):
        """One suffix chunk of a whole-admission prefix hit: slot ``slot``
        consumes ``n`` of the ``rows`` (a padded ``(Tq,)`` chunk of its
        prompt) while every other slot rides inert — their parked tokens
        pass through untouched because the token matrix is built from the
        DEVICE token vector (an ``await`` slot's first token only exists
        there until the next batched fetch). On the FIRST suffix chunk
        the slot's length resets to ``start`` (= the matched prefix
        length): a no-op where the contiguous hit gather already set it,
        the one place the device learns the hit under the paged layout.
        Emits the first sampled token into the token vector on the final
        chunk."""
        S, tq = self.slots, rows.shape[0]
        tokens = jnp.zeros((S, tq), jnp.int32).at[:, 0].set(tok_vec)
        tokens = lax.dynamic_update_slice(tokens, rows[None, :], (slot, 0))
        one_hot = jnp.arange(S, dtype=jnp.int32) == slot
        n_vec = jnp.where(one_hot, n, 0).astype(jnp.int32)
        emit = one_hot & last
        reset = one_hot & first
        reset_val = jnp.where(one_hot, start, 0).astype(jnp.int32)
        return self._mixed_fn(params, tokens, n_vec, reset, reset_val,
                              emit, cache, keys, temp, topk, idx, lp_vec)

    def _sibling_first_fn(self, tok_vec, lp_vec, row, key, temp, topk,
                          slot):
        """Park a forked sibling's FIRST token: sample from the parent's
        stashed prompt-end logits ``row`` under the child's key (branch
        index folded in at seeding) and write token + logprob into the
        device vectors — the child then rides the existing ``await``
        machinery, surfacing at the next batched fetch. Greedy children
        argmax the identical row, so every sibling's first token is
        bit-identical to an independent admission's."""
        tok_s, lp_s = self._sample_emit(
            row[None], key[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), jnp.zeros((1,), jnp.int32),
        )
        tok_vec = lax.dynamic_update_index_in_dim(tok_vec, tok_s[0],
                                                  slot, axis=0)
        lp_vec = lax.dynamic_update_index_in_dim(lp_vec, lp_s[0],
                                                 slot, axis=0)
        return tok_vec, lp_vec

    def _tree_first_fn(self, row, branch_ix, salt, temp, topk):
        """Sample every tree sibling's FIRST token from the parent's
        stashed prompt-end logits (ISSUE 20): branch ``b`` draws under
        fold_in(fold_in(fold_in(base, salt), b), 0) — the exact chain
        :meth:`_sibling_first_fn` evaluates for a fork-slot sibling of
        the same index, so the two family layouts' first tokens are
        bit-identical. One tiny dispatch per family start."""
        n = branch_ix.shape[0]
        keys = jax.vmap(lambda b: jax.random.fold_in(jax.random.fold_in(
            self._base_key, salt), b))(branch_ix)
        rows = jnp.broadcast_to(row, (n, row.shape[-1]))
        return sample_slots(
            rows, jnp.full((n,), temp, jnp.float32),
            jnp.full((n,), topk, jnp.int32), keys,
            jnp.zeros((n,), jnp.int32),
        )

    def _spec_step(self, params, mat, tok_vec, use_dev0, n_tok, reset,
                   reset_val, emit, depth, bits, cache, keys, temp, topk,
                   idx, lp_vec, salt, branch_m, ridx_m):
        """THE verify-tick program (speculate and/or token-tree sibling
        decode): the same mixed-Tq step as :meth:`_mixed_fn` plus the
        verify extras —

        - row 0 of each slot comes from the DEVICE token vector when
          ``use_dev0`` (a whole-admission ``await`` slot's parked first
          token only exists there); every other row from the host-built
          matrix (the host knows every committed/replayed token);
        - ``depth``/``bits`` (tree ticks only): packed tree rows take
          RoPE position ``length + depth[row]`` and attend under the
          per-slot ancestor mask instead of row-order causal — chain
          slots ride ``arange``/lower-triangular defaults, which are the
          causal rule bit-for-bit;
        - a per-ROW sample of every logits row — the accept walk's
          input under speculation (greedy rows are pure argmax, exactly
          the legacy rule; sampled rows draw the Leviathan coupling
          sample) and the sibling tips under tree decode. Row keys are
          the reproducibility chain re-derived IN-PROGRAM:
          ``branch_m[s, r] >= 0`` (a sibling row of branch b at stream
          index ``ridx_m[s, r]``) folds (salt, branch, index) into the
          engine's base key — the fork-slot path's exact chain;
          ``branch_m[s, r] < 0`` (a spec verify row) folds the stream
          index into the slot's installed request key.

        ``reset_val`` doubles as the rollback: a verify slot always
        resets to its host-side committed length, which un-counts the
        rows a previous tick rejected (or a tree slot's replayed
        suffix).
        """
        tokens = mat.at[:, 0].set(jnp.where(use_dev0, tok_vec, mat[:, 0]))
        length = jnp.where(reset, reset_val, cache.length)
        cache = dataclasses.replace(cache, length=length)
        kw = dict(self._fs_kw)
        if self.quantize:
            kw["quant_kernel"] = self.quant_kernel
        if depth is not None:
            kw["positions"] = length[:, None] + depth
            kw["tree_mask"] = bits
        logits, new_cache = forward_step(
            params, tokens, cache, self.cfg, n_tokens=n_tok, **kw
        )
        row = jnp.maximum(n_tok - 1, 0)
        last = jnp.take_along_axis(logits, row[:, None, None], axis=1)[:, 0]
        # Column 0 keeps the mixed-step emit contract verbatim (final
        # chunks sample their first token under the slot key, parked
        # tokens/logprobs ride through) — temperature-0 slots reduce to
        # the legacy greedy argmax bit-for-bit.
        tok_s, lp_s = self._sample_emit(last, keys, temp, topk, idx)
        nxt = jnp.where(emit, tok_s, tokens[:, 0])
        lp_out = jnp.where(emit, lp_s, lp_vec)

        def _row_key(key, s, b, r):
            tree_k = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(self._base_key, s), b), r)
            return jnp.where(b < 0, jax.random.fold_in(key, r), tree_k)

        row_keys = jax.vmap(
            lambda key, s, bs, rs: jax.vmap(
                lambda b, r: _row_key(key, s, b, r))(bs, rs)
        )(keys, salt, branch_m, ridx_m)
        all_tok, all_lp = sample_rows(logits, temp, topk, row_keys)
        # One fused (S, 1+Tq, 2) output = ONE host fetch per tick: lane
        # 0 tokens, lane 1 bitcast logprobs; row 0 the token/logprob
        # vectors (the awaits/parked contract), the rest the per-row
        # draws.
        col0 = jnp.stack(
            [nxt, lax.bitcast_convert_type(lp_out, jnp.int32)], axis=-1,
        )[:, None]
        rest = jnp.stack(
            [all_tok, lax.bitcast_convert_type(all_lp, jnp.int32)],
            axis=-1,
        )
        fused = jnp.concatenate([col0, rest], axis=1)
        # ``last`` rides out as a device carry exactly like the mixed
        # step's: a family admitted on a verify tick still stashes its
        # prompt-end logits row for the fork/tree start. Fetched never.
        return nxt, lp_out, fused, last, new_cache

    def _spec_lin_fn(self, params, mat, tok_vec, use_dev0, n_tok, reset,
                     reset_val, emit, cache, keys, temp, topk, idx,
                     lp_vec, salt, branch_m, ridx_m):
        """Verify tick with chain drafts only — pure causal, no mask or
        position operands (one program family shared with chunk ticks)."""
        return self._spec_step(params, mat, tok_vec, use_dev0, n_tok,
                               reset, reset_val, emit, None, None, cache,
                               keys, temp, topk, idx, lp_vec, salt,
                               branch_m, ridx_m)

    def _spec_tree_fn(self, params, mat, tok_vec, use_dev0, n_tok, reset,
                      reset_val, emit, depth, bits, cache, keys, temp,
                      topk, idx, lp_vec, salt, branch_m, ridx_m):
        """Verify tick with >= 1 packed token tree — draft trees
        (SpecInfer, arXiv:2305.09781) and/or sibling-branch bundles
        (ISSUE 20): per-slot depths and ancestor masks ride along."""
        return self._spec_step(params, mat, tok_vec, use_dev0, n_tok,
                               reset, reset_val, emit, depth, bits,
                               cache, keys, temp, topk, idx, lp_vec,
                               salt, branch_m, ridx_m)

    def _compact_fn(self, cache, start, src, n):
        """Batched commit compaction: move each verifying slot's accepted
        tree rows contiguous (see models.decode.compact_decode_window);
        slots with n=0 are bit-identically untouched."""
        return compact_decode_window(cache, start, src, n)

    def _prefill_fn(self, params, prompt, plen, key, temp, topk):
        """Legacy whole-prompt admission: prefill one request into a fresh
        prompt-bucket-sized B=1 cache (NOT a full-capacity one — the
        bucket bounds both the allocation and the attention work).

        ``prompt`` is padded to its bucket; rows at positions >= plen are
        pad garbage, so after the step they are zeroed — the inserted slot
        (and, under ``quantize``, its frozen per-channel scales) is then
        bit-identical to an unpadded prefill, and one compile serves the
        whole bucket.
        """
        cfg = self.cfg
        bucket = prompt.shape[1]
        shape = (cfg.n_layers, 1, cfg.n_kv_heads, bucket, cfg.d_head)
        mini = KVCache(
            k=jnp.zeros(shape, cfg.dtype),
            v=jnp.zeros(shape, cfg.dtype),
            length=jnp.zeros((1,), jnp.int32),
        )
        logits, mini = forward_step(params, prompt, mini, cfg,
                                    **self._prefill_kw)
        valid = (
            jnp.arange(bucket, dtype=jnp.int32) < plen
        )[None, None, None, :, None]
        k = jnp.where(valid, mini.k, 0)
        v = jnp.where(valid, mini.v, 0)
        last = lax.dynamic_index_in_dim(logits, plen - 1, axis=1,
                                        keepdims=False)  # (1, V)
        tok_s, lp_s = self._sample_emit(
            last, key[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), jnp.zeros((1,), jnp.int32),
        )
        tok, lp = tok_s[0], lp_s[0]
        if self.quantize:
            if self._paged:
                # Per-BLOCK quantization (ISSUE 13): each prompt block's
                # scale is its own absmax, so the published blocks are
                # self-contained and shareable through the radix tree.
                kq, vq, ks, vs = quantize_paged_blocks(
                    k, v, self.kv_block, plen
                )
                return (kq, vq, ks, vs, tok, lp), last
            qc = quantize_cache(KVCache(k=k, v=v, length=mini.length))
            return (qc.k, qc.v, qc.k_scale, qc.v_scale, tok, lp), last
        return (k, v, tok, lp), last

    def _insert_fn(self, cache, tok_vec, lp_vec, slot, payload, plen):
        """Place a bucket-sized prefilled B=1 cache into slot ``slot`` of
        the batch cache (k/v rows, per-slot length, first token). The
        slot's rows beyond the bucket keep stale bytes from the previous
        occupant — every row >= the new length is masked future, and
        decode overwrites them before they can become visible. Under the
        paged layout the rows scatter through the slot's block table
        (the engine mapped blocks covering ``[0, plen)`` first)."""
        if self.quantize:
            k_new, v_new, ks_new, vs_new, first, lp = payload
        else:
            k_new, v_new, first, lp = payload
        lp_vec = lax.dynamic_update_index_in_dim(lp_vec, lp, slot, axis=0)
        if self._paged:
            plen_i = jnp.asarray(plen, jnp.int32)
            if self.quantize:
                new_cache = paged_insert_slot(
                    cache, slot, k_new, v_new, plen_i, ks_new, vs_new
                )
            else:
                new_cache = paged_insert_slot(
                    cache, slot, k_new, v_new, plen_i
                )
            tok_vec = lax.dynamic_update_index_in_dim(
                tok_vec, first, slot, axis=0
            )
            return new_cache, tok_vec, lp_vec
        put = lambda buf, new: lax.dynamic_update_slice(
            buf, new.astype(buf.dtype), (0, slot, 0, 0, 0)
        )
        length = lax.dynamic_update_index_in_dim(
            cache.length, jnp.asarray(plen, jnp.int32), slot, axis=0
        )
        if self.quantize:
            new_cache = QuantKVCache(
                k=put(cache.k, k_new), v=put(cache.v, v_new),
                k_scale=put(cache.k_scale, ks_new),
                v_scale=put(cache.v_scale, vs_new),
                length=length,
            )
        else:
            new_cache = KVCache(
                k=put(cache.k, k_new), v=put(cache.v, v_new), length=length
            )
        tok_vec = lax.dynamic_update_index_in_dim(tok_vec, first, slot, axis=0)
        return new_cache, tok_vec, lp_vec

    def _stage_chunk_fn(self, params, tokens, n_tok, staging, reset,
                        reset_val):
        """One mid-prompt chunk into the exact staging cache (quantized
        chunked admission). Logits are unused here, so XLA prunes the
        output head. ``reset_val`` mirrors the mixed step's: the first
        chunk sets the staged length to the prefix-hit match (0 cold)."""
        length = jnp.where(reset, reset_val, staging.length)
        staging = dataclasses.replace(staging, length=length)
        _, staging = forward_step(
            params, tokens, staging, self.cfg, n_tokens=n_tok,
            **self._prefill_kw,
        )
        return staging

    def _stage_final_fn(self, params, tokens, n_tok, staging, cache,
                        tok_vec, lp_vec, slot, plen, reset, reset_val,
                        key, temp, topk, lo=0):
        """The final chunk: finish the staged exact prefill, sample the
        first token from the last valid row, mask the stale tail, quantize
        the staged prompt (per-slot frozen channel scales on the
        contiguous layout; per-BLOCK scalars on the paged one — the
        quantize-after-prefill contract, at each layout's granularity),
        and insert slot rows + scales + length + first token into the
        batch cache — one dispatch, no host sync (the token rides the
        per-tick fetch). Under the paged layout the insert scatters
        through the slot's block table, skipping token positions below
        ``lo`` — a prefix hit's matched blocks are SHARED (their staged
        rows are the dequantized originals, which re-quantize to
        bit-identical int8, so nothing is lost by not rewriting them)."""
        length = jnp.where(reset, reset_val, staging.length)
        staging = dataclasses.replace(staging, length=length)
        logits, staging = forward_step(
            params, tokens, staging, self.cfg, n_tokens=n_tok,
            **self._prefill_kw,
        )
        idx = jnp.maximum(n_tok - 1, 0)
        last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
        tok_s, lp_s = self._sample_emit(
            last, key[None], jnp.reshape(temp, (1,)),
            jnp.reshape(topk, (1,)), jnp.zeros((1,), jnp.int32),
        )
        first, lp = tok_s[0], lp_s[0]
        lp_vec = lax.dynamic_update_index_in_dim(lp_vec, lp, slot, axis=0)
        valid = (
            jnp.arange(self.cache_len, dtype=jnp.int32) < plen
        )[None, None, None, :, None]
        k_masked = jnp.where(valid, staging.k, 0)
        v_masked = jnp.where(valid, staging.v, 0)
        if self._paged:
            kq, vq, ks, vs = quantize_paged_blocks(
                k_masked, v_masked, self.kv_block, plen
            )
            new_cache = paged_insert_slot(
                cache, slot, kq, vq, jnp.asarray(plen, jnp.int32),
                ks, vs, lo=lo,
            )
            tok_vec = lax.dynamic_update_index_in_dim(tok_vec, first,
                                                      slot, axis=0)
            return staging, new_cache, tok_vec, lp_vec, last
        qc = quantize_cache(KVCache(
            k=k_masked,
            v=v_masked,
            length=staging.length,
        ))
        put = lambda buf, new: lax.dynamic_update_index_in_dim(
            buf, new[:, 0], slot, axis=1
        )
        new_cache = QuantKVCache(
            k=put(cache.k, qc.k), v=put(cache.v, qc.v),
            k_scale=put(cache.k_scale, qc.k_scale),
            v_scale=put(cache.v_scale, qc.v_scale),
            length=lax.dynamic_update_index_in_dim(
                cache.length, jnp.asarray(plen, jnp.int32), slot, axis=0
            ),
        )
        tok_vec = lax.dynamic_update_index_in_dim(tok_vec, first, slot,
                                                  axis=0)
        return staging, new_cache, tok_vec, lp_vec, last

    # -- ingress-facing control (thread-safe) ------------------------------

    def prefix_stats(self) -> Dict[str, Any]:
        """Lifetime radix-cache counters (hits/misses/tokens_reused/...),
        empty when the cache is off. Public so a fleet bench/test can
        diff reuse across arms of ONE live serve() run (ServeReport's
        per-run prefix block only lands when the run drains)."""
        return {} if self._prefix is None else dict(self._prefix.stats())

    def cancel(self, uid: int) -> None:
        """Cancel request ``uid`` (any thread; e.g. a client disconnect).

        Records the uid in the control mailbox; the tick loop's sweep
        applies it at the next tick start — queued-unadmitted requests
        finish unserved, in-flight requests retire mid-stream (slot
        freed, prefix pins released, paged blocks unmapped back to the
        pool). Unknown/already-finished uids are a no-op (the client
        may disconnect after its stream completed)."""
        with self._ctl_lock:
            self._cancel_uids.add(uid)

    def fork(self, uid: int) -> None:
        """Branch live request ``uid`` mid-generation (any thread).

        Records the uid in the fork mailbox; the tick loop's control
        sweep applies it — the request's newest branch gets a fresh
        slot whose block table SHARES every full ancestor block
        (refcount++, zero KV bytes) with only the partial tail block
        copied, and continues sampling under its own PRNG key. The
        branch finishes as one more indexed :class:`RequestResult`
        under the same uid ("join" = the family's results/callbacks).
        Scarce slots/blocks defer the fork a couple of sweeps; a uid
        that is not (or no longer) live ages out as a no-op."""
        with self._ctl_lock:
            self._fork_uids.append(uid)

    def _take_forks(self) -> List[int]:
        """Drain the fork mailbox (loop side), oldest first."""
        with self._ctl_lock:
            out = self._fork_uids
            self._fork_uids = []
            return out

    def request_drain(self) -> None:
        """Begin graceful drain (any thread; e.g. a SIGTERM handler).

        The loop stops admitting: visible-but-unadmitted work is shed
        (outcome ``shed``), the source is closed, in-flight requests run
        to completion, and ``serve()`` returns — the caller then flushes
        telemetry and exits."""
        with self._ctl_lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._ctl_lock:
            return self._draining

    @property
    def all_slots_free(self) -> bool:
        """True when no request occupies a slot (single list read —
        safe to poll from harness/monitor threads)."""
        return all(st == "free" for st in self._slot_state)

    def _take_control(self) -> Tuple[Set[int], bool]:
        """Drain the cancel mailbox and read the drain flag (loop side)."""
        with self._ctl_lock:
            cancels = self._cancel_uids
            self._cancel_uids = set()
            return cancels, self._draining

    def leak_report(self) -> Dict[str, int]:
        """The no-leak invariant, as numbers (chaos-harness contract).

        After a drained run — every request retired, however it exited —
        the engine must hold NO per-request resources: no slot-private
        blocks, no unspent reservations, no pinned radix nodes; the only
        legitimate pool occupancy is the radix tree's retained cache
        (``blocks_used == blocks_cached``). A disconnect storm that
        violates this leaked memory."""
        out = {
            "blocks_private": (sum(len(s) for s in self._slot_private)
                               if self._paged else 0),
            "blocks_used": self._pool.used if self._paged else 0,
            "blocks_reserved": self._pool.reserved if self._paged else 0,
            # CoW-shared fork ancestors still refcounted by some slot
            # (ISSUE 15) — 0 after a drain, like blocks_private.
            "blocks_shared": self._pool.shared_count if self._paged else 0,
            "blocks_cached": 0,
            "pins": 0,
        }
        if self._host_pool is not None:
            # Host-tier occupancy is legitimate retained cache (like
            # blocks_cached), surfaced for the harness's accounting.
            out["host_blocks_used"] = self._host_pool.used
        if self._prefix is not None:
            out["blocks_cached"] = self._prefix.blocks_used
            out["pins"] = self._prefix.total_pins()
        elif self._paged:
            # No prefix tree: every used block is slot-private, so a
            # drained engine must be at used == 0 exactly.
            pass
        return out

    def slots_snapshot(self) -> List[Dict[str, Any]]:
        """Per-slot live view for the obs server's ``/slots`` endpoint
        (ISSUE 16): state, occupant uid/branch, generated length, and
        committed cache length. Called from HTTP handler threads while
        the engine thread mutates the arrays — every read here is one
        GIL-atomic list index (ints, strings, a Request ref), so the
        worst case is a snapshot one tick stale, never a torn value."""
        out: List[Dict[str, Any]] = []
        for i in range(self.slots):
            req = self._slot_req[i]
            out.append({
                "slot": i,
                "state": self._slot_state[i],
                "uid": None if req is None else req.uid,
                "index": self._slot_index[i] if req is not None else 0,
                "tokens": len(self._slot_tokens[i]),
                "clen": self._slot_clen[i],
                **({"nblocks": self._slot_nblocks[i]}
                   if self._paged else {}),
            })
        return out

    # -- per-request callbacks (engine thread) -----------------------------

    def _deliver_token(self, req: Request, index: int, tok: int) -> None:
        """Raw token delivery: branch callback when wired (any index),
        else the legacy single-stream callback for branch 0 only."""
        cb = req.on_branch_token
        if cb is not None:
            try:
                cb(index, tok)
            except Exception:
                log.exception("on_branch_token failed (rid %s)", req.uid)
            return
        if index == 0 and req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                log.exception("on_token callback failed (rid %s)", req.uid)

    def _deliver_finish(self, req: Request, index: int,
                        result: RequestResult) -> None:
        cb = req.on_branch_finish
        if cb is not None:
            try:
                cb(index, result)
            except Exception:
                log.exception("on_branch_finish failed (rid %s)", req.uid)
            return
        if index == 0 and req.on_finish is not None:
            try:
                req.on_finish(result)
            except Exception:
                log.exception("on_finish callback failed (rid %s)", req.uid)

    def _push_token(self, req: Request, tok: int, index: int = 0) -> None:
        fam = self._families.get(req.uid)
        if fam is not None and fam.best_of:
            # Server-side selection: nothing streams until the family
            # joins and _emit_best_of replays the winner.
            return
        self._deliver_token(req, index, tok)

    def _notify_finish(self, req: Request, result: RequestResult,
                       fam: Optional["_ForkFamily"] = None) -> None:
        fam = fam if fam is not None else self._families.get(req.uid)
        if fam is not None and fam.best_of:
            return  # the family join emits the one winner finish
        self._deliver_finish(req, result.index, result)

    def _finish_unadmitted(self, req: Request, tick: int, outcome: str,
                           results: List[RequestResult],
                           visible_at: float, now: float) -> None:
        """Retire a request that never reached a slot (cancelled,
        deadline-expired, or shed while queued; invalid live
        submission). No engine resources to release — only the
        result(s), the outcome counter, and the client callback. An
        n/best_of family rejects whole: one result PER requested
        completion, so a client counting n finishes always converges."""
        branches = self._branches(req)
        for index in range(branches):
            res = RequestResult(
                uid=req.uid,
                tokens=[],
                prompt_len=len(req.prompt),
                arrival_tick=req.arrival_tick,
                admit_tick=-1,
                finish_tick=tick,
                queue_wait_s=max(now - visible_at, 0.0),
                completion_s=max(now - visible_at, 0.0),
                outcome=outcome,
                ttft_s=0.0,
                index=index,
            )
            results.append(res)
            if outcome in (OUTCOME_DEADLINE, OUTCOME_SHED, OUTCOME_ERROR):
                # A categorical SLO miss: the system failed to serve it.
                # (Client cancellations are not the server's miss.)
                self.slo.observe_miss()
            if obs.REGISTRY.enabled:
                _REQUESTS.labels(outcome=outcome).inc()
            self._deliver_finish(req, index, res)
        if obs.TRACER.active:
            obs.instant("request_rejected", cat="serving", args={
                "rid": req.uid, "tick": tick, "outcome": outcome,
                "branches": branches,
            })

    # -- scheduler --------------------------------------------------------

    def _free_slots(self) -> List[int]:
        return [i for i, st in enumerate(self._slot_state) if st == "free"]

    @staticmethod
    def _branches(req: Request) -> int:
        """How many completions request ``req`` fans out to (ISSUE 15):
        ``best_of`` branches when server-side selection is on, else
        ``n`` — always >= 1."""
        bo = req.best_of if req.best_of is not None else 0
        return max(int(req.n), int(bo), 1)

    # Overridden to False on engines that cannot expand fork families
    # (the disaggregated pair's workers — a family would need slots on
    # both sides of the handoff).
    _fork_ok = True

    # -- token-tree sibling decode (ISSUE 20) -----------------------------

    def _tree_span(self, req: Request) -> int:
        """Worst-case token span of an admission tree family: the frozen
        prompt rows plus every branch's full replayed suffix (each
        branch grows to ``max_new - 1`` suffix rows before its last
        token retires it), floored at the plain single-branch span the
        slot needs after the family collapses to one survivor."""
        k = self._branches(req)
        plen = len(req.prompt)
        return max(plen + k * (req.max_new_tokens - 1),
                   plen + req.max_new_tokens)

    def _tree_sibling_ok(self, req: Request) -> bool:
        """Can this n>1 / best-of-n request decode as a token tree in
        ONE slot? Requires the tree-mask attention path, a paged pool,
        and the whole family's worst-case row bundle fitting both the
        verify Tq cap (int32 bitmask: 32 rows) and the cache window.
        False falls back to the PR-15 fork-slot path — same tokens,
        k slots."""
        k = self._branches(req)
        if k <= 1 or not self._tree_sampling or not self._paged:
            return False
        if self._speculate or not self._tree_ok or not self._fork_ok:
            return False
        rows = k * (req.max_new_tokens - 1)
        if rows > self._spec_rows_cap:
            return False
        return len(req.prompt) + rows <= self.cache_len

    # Admission-scoped host-tier attribution scratch (ISSUE 16): counts
    # accumulated while _admit runs — prefix-path restores by
    # _paged_hit, demote flushes a dry allocator forces mid-admission —
    # and folded into the request's ledger once it opens at the end of
    # _admit. Plain ints, engine-thread only.
    _admitting = False
    _adm_restored = 0
    _adm_demoted = 0

    def _validate(self, req: Request) -> None:
        plen = len(req.prompt)
        if plen < 1:
            raise ValueError(f"request {req.uid}: empty prompt")
        if req.temperature is not None and req.temperature < 0:
            raise ValueError(
                f"request {req.uid}: temperature must be >= 0"
            )
        if req.top_k is not None and req.top_k < 0:
            raise ValueError(f"request {req.uid}: top_k must be >= 0")
        if req.n < 1:
            raise ValueError(f"request {req.uid}: n must be >= 1")
        if req.best_of is not None and req.best_of > 1 and req.n != 1:
            raise ValueError(
                f"request {req.uid}: best_of runs server-side selection "
                f"and streams ONE winner — it requires n == 1"
            )
        if req.fork_at is not None and req.fork_at < 1:
            raise ValueError(f"request {req.uid}: fork_at must be >= 1")
        branches = self._branches(req)
        if branches > 1:
            if not self._paged:
                raise ValueError(
                    f"request {req.uid}: n/best_of > 1 forks over "
                    f"shared KV blocks — it requires kv_layout='paged'"
                )
            if self._speculate:
                raise ValueError(
                    f"request {req.uid}: n/best_of > 1 is not supported "
                    f"with speculate=True (fork branches are sampled; "
                    f"speculation is greedy-only)"
                )
            if not self._fork_ok:
                raise ValueError(
                    f"request {req.uid}: n/best_of > 1 is not supported "
                    f"on this engine (disaggregated workers cannot "
                    f"expand fork families; mid-generation fork(uid) "
                    f"on the decode pool still works)"
                )
            if branches > self.slots and not self._tree_sibling_ok(req):
                # Tree-sibling families (ISSUE 20) decode every branch
                # in ONE slot; only the fork-slot fallback needs a slot
                # per branch.
                raise ValueError(
                    f"request {req.uid}: {branches} parallel branches "
                    f"exceed the engine's {self.slots} slots (the whole "
                    f"family decodes concurrently)"
                )
        if req.max_new_tokens < 1:
            # The prefill itself samples one token, so a zero budget
            # is unservable — same contract as generate().
            raise ValueError(
                f"request {req.uid}: max_new_tokens must be >= 1, "
                f"got {req.max_new_tokens}"
            )
        if plen + req.max_new_tokens > self.cache_len:
            raise ValueError(
                f"request {req.uid}: prompt {plen} + max_new "
                f"{req.max_new_tokens} exceeds slot capacity {self.cache_len}"
            )
        if self._paged:
            # The clean over-subscription failure: a request whose worst
            # case exceeds the WHOLE pool can never be admitted — reject
            # it here, in English, instead of wedging the queue (a
            # merely-scarce pool defers admission instead; see serve()).
            need = -(-(plen + req.max_new_tokens) // self.kv_block)
            if need > self.kv_blocks:
                raise ValueError(
                    f"request {req.uid}: worst case needs {need} KV "
                    f"blocks (prompt {plen} + max_new "
                    f"{req.max_new_tokens} at --kv-block {self.kv_block}) "
                    f"but the --kv-blocks pool holds {self.kv_blocks}; "
                    f"raise --kv-blocks or shrink the request"
                )
            branches = self._branches(req)
            if branches > 1:
                if self._tree_sibling_ok(req):
                    # Tree-sibling worst case: the ONE slot's frozen
                    # ancestor rows plus every branch's packed suffix
                    # window (never more than the fork-slot family
                    # below — the suffix rows share every ancestor).
                    fam = -(-self._tree_span(req) // self.kv_block)
                else:
                    # Each sibling's worst case is its NEW blocks only —
                    # everything below the fork point is shared (the CoW
                    # economics this subsystem exists for).
                    fam = need + (branches - 1) * (
                        need - (plen - 1) // self.kv_block
                    )
                if fam > self.kv_blocks:
                    raise ValueError(
                        f"request {req.uid}: a {branches}-branch family "
                        f"worst-cases at {fam} KV blocks (shared "
                        f"ancestors counted once) but the pool holds "
                        f"{self.kv_blocks}; raise --kv-blocks or shrink "
                        f"the request"
                    )

    # -- paged-pool bookkeeping -------------------------------------------

    def _paged_reserve(self, req: Request) -> Optional[Tuple[int, List[Any],
                                                             int, int]]:
        """Match (pinning the path) + reserve the admission's worst-case
        private blocks; ``None`` defers the admission — the request waits
        in the queue until retires/evictions free blocks. The prefix
        hit's DEVICE-resident shared blocks subtract from the reservation
        (the sharing that lets slot-count exceed pool bytes — int8
        included now that per-block scales make its blocks shareable);
        a matched node sitting on the HOST tier still costs one
        reservation, because restoring it allocates a fresh device block
        (the restore consumes exactly that reservation in _paged_hit).

        A fork family (``n``/``best_of`` > 1, ISSUE 15) reserves
        ATOMICALLY: the parent's blocks plus each sibling's worst-case
        NEW blocks (its total minus the full ancestors it will share) —
        so sibling forks later never fail, and two half-reserved
        families can never deadlock the pool against each other. The
        family extra is returned separately and held by the family
        until the forks consume it."""
        total = -(-(len(req.prompt) + req.max_new_tokens) // self.kv_block)
        matched, nodes = 0, []
        if self._paged_prefix:
            matched, nodes = self._prefix.match(
                np.asarray(req.prompt, np.int32), record=False
            )
        dev_matched = sum(1 for n in nodes if n.tier == TIER_DEVICE)
        branches = self._branches(req)
        fam_extra = 0
        if branches > 1:
            if self._tree_sibling_ok(req):
                # Token-tree sibling admission (ISSUE 20): ONE slot
                # holds the whole family — its reservation is the
                # packed window's worst case, no per-sibling extra.
                total = -(-self._tree_span(req) // self.kv_block)
            else:
                sib = total - (len(req.prompt) - 1) // self.kv_block
                fam_extra = (branches - 1) * sib
        needed = total - dev_matched
        if not self._pool.reserve(needed + fam_extra):
            if nodes:
                self._prefix.release(nodes)
            return None
        if self._paged_prefix:
            self._prefix.record_match(matched)
        return matched, nodes, needed, fam_extra

    def _ensure_blocks(self, slot: int, tokens_needed: int) -> None:
        """Map physical blocks covering ``[0, tokens_needed)`` tokens of
        ``slot`` — called before every dispatch that writes the slot.
        Allocation is backed by the admission's reservation, so it cannot
        fail; a full free list recycles LRU refcount-0 prefix leaves."""
        if not self._paged:
            return
        need = -(-tokens_needed // self.kv_block)
        grew = self._slot_nblocks[slot] < need
        while self._slot_nblocks[slot] < need:
            assert self._slot_reserve[slot] > 0, (
                f"slot {slot} outgrew its block reservation"
            )
            bid = self._pool.alloc()
            self._slot_reserve[slot] -= 1
            self._host_table[slot, self._slot_nblocks[slot]] = bid
            self._slot_private[slot].add(bid)
            self._slot_nblocks[slot] += 1
            self._table_dirty = True
        if grew and obs.REQLOG.enabled:
            # Re-integrate the ledger's device-block-seconds at the new
            # block count (once per block boundary, not per token).
            rq = self._slot_req[slot]
            if rq is not None:
                obs.REQLOG.blocks(rq.uid, self._slot_nblocks[slot])

    def _sync_table(self) -> None:
        """Push the host block table to the device when it changed — the
        ONE host→device transfer a table update costs (a few hundred
        int32s; the contiguous layout's prefix hit moved the KV itself)."""
        if self._paged and self._table_dirty:
            self.cache = dataclasses.replace(
                self.cache, table=jnp.asarray(self._host_table)
            )
            self._table_dirty = False

    def attach_host_tier(self, host_pool: HostBlockPool) -> None:
        """Wire ``host_pool`` as this engine's KV demotion tier: build
        the demote-gather / restore-scatter jits (the ONE home of their
        donation recipe) and register the staged-flush hook on the
        allocator. Called by ``__init__`` for ``host_blocks=`` and by
        ``DisaggServer`` to make the prefill worker the SHARED tree's
        tier engine (there the pool was built by the pair, the index
        already points at it, and the relayed pool arrays make this
        engine's cache the live pool whichever worker dispatched last)."""
        self._host_pool = host_pool
        self.host_blocks = host_pool.blocks
        self._demote_gather = jax.jit(gather_kv_blocks)
        self._restore_scatter = jax.jit(
            scatter_kv_blocks,
            donate_argnums=(0, 1) if not self.quantize
            else (0, 1, 5, 6),
        )
        self._pool.set_demote_flusher(self._flush_demotions)

    def _flush_demotions(self) -> int:
        """Complete every staged demotion: ONE jitted gather over the
        batch of pending device blocks, one D2H fetch, then the blocks
        free. Called at the end of each tick (off the tick's dispatch
        path — the gather queues behind the tick's step and the fetch
        happens where the loop would otherwise idle) and by a dry
        allocator mid-tick (rare; the batch amortisation is the point).
        Returns how many device blocks it freed."""
        hp = self._host_pool
        if hp is None or not hp.pending:
            return 0
        items = hp.take_pending()
        rows = [r for r, _ in items]
        bids = [b for _, b in items]
        nb = _bucket(len(bids), max(self._npb, len(bids)), floor=1)
        ids = np.zeros((nb,), np.int32)  # pad gathers block 0; ignored
        ids[:len(bids)] = bids
        if self.quantize:
            out = self._demote_gather(
                self.cache.k, self.cache.v, jnp.asarray(ids),
                self.cache.k_scale, self.cache.v_scale,
            )
        else:
            out = self._demote_gather(
                self.cache.k, self.cache.v, jnp.asarray(ids)
            )
        hp.commit(rows, *out)  # the D2H fetch happens inside commit
        for b in bids:
            self._pool.free_demoted(b)
        if self._admitting:
            # A dry allocator forced this flush mid-admission: charge
            # the demotions to the admitting request's ledger scratch.
            self._adm_demoted += len(bids)
        if obs.TRACER.active:
            obs.instant("kv_demote_flush", cat="serving", args={
                "blocks": len(bids),
            })
        return len(bids)

    def _admit(self, req: Request, slot: int, tick: int,
               visible_at: float,
               resv: Optional[Tuple[int, List[Any], int, int]] = None) -> float:
        # Queue wait ends the moment the scheduler takes the request —
        # BEFORE any prefill work runs (prefill, including a first-bucket
        # jit compile, is service time, not queueing).
        waited = max(time.monotonic() - visible_at, 0.0)
        self._admitting = True
        self._adm_restored = 0
        self._adm_demoted = 0
        self._slot_req[slot] = req
        self._slot_tokens[slot] = []
        self._slot_admit[slot] = (tick, visible_at)
        self._slot_max_tbt[slot] = 0.0
        self._slot_ttft[slot] = 0.0  # stale-occupant guard: a request
        # retired before its first token must report ttft 0, not the
        # previous occupant's
        self._slot_wait[slot] = waited
        self._chunk_k[slot] = 0
        # Sampling state (ISSUE 15): per-slot temperature/top-k (engine
        # defaults unless the request overrides) and the request's PRNG
        # key — fold_in(base, seed-or-uid) at branch 0. Pure host/np
        # writes plus one tiny key dispatch; the vectors ride every
        # dispatch as operands.
        self._slot_index[slot] = 0
        self._slot_cum_lp[slot] = 0.0
        self._slot_shared[slot] = set()
        self._temp_np[slot] = (self.temperature if req.temperature is None
                               else req.temperature)
        self._topk_np[slot] = (self.top_k if req.top_k is None
                               else req.top_k)
        salt = (req.seed if req.seed is not None else req.uid) & 0x7FFFFFFF
        self._salt_np[slot] = salt
        self._keys = self._seed_key(self._keys, jnp.int32(slot),
                                    jnp.int32(salt), jnp.int32(0))
        self.slo.observe_queue_wait(waited)
        # Prefix reuse happens FIRST: the matched length decides how much
        # prompt is left to prefill (and rides the request span below).
        self._prompt_np[slot] = np.asarray(req.prompt, np.int32)
        if self._speculate:
            plen = len(self._prompt_np[slot])
            self._hist_buf[slot, :plen] = self._prompt_np[slot]
            self._hist_len[slot] = plen
        if self._paged:
            # The reservation was taken (and the radix path pinned) by
            # _paged_reserve in the admit loop — here the slot takes
            # ownership of both.
            _, _, needed, _ = resv
            self._slot_reserve[slot] = needed
            self._slot_private[slot] = set()
            self._slot_nblocks[slot] = 0
        if self._paged_prefix:
            matched = self._paged_hit(req, slot, tick, resv)
        else:
            matched = self._prefix_admit(req, slot, tick)
        self._prefill_start[slot] = matched
        self._slot_prefix_hit[slot] = matched
        # The request's life as ONE span (admit -> retire; rid in args so
        # a Perfetto query groups every event of one request), plus an
        # admitted instant on the timeline.
        self._slot_span[slot] = obs.span(
            f"request:{req.uid}", cat="serving",
            args=None if not obs.TRACER.active else {
                "rid": req.uid, "slot": slot, "admit_tick": tick,
                "prompt_len": len(req.prompt),
                **({"prefix_hit_len": matched}
                   if self._prefix is not None else {}),
                **({"trace_id": req.trace[0]} if req.trace else {}),
            },
        )
        if obs.TRACER.active:
            obs.instant("request_admitted", cat="serving", args={
                "rid": req.uid, "slot": slot, "tick": tick,
                "queue_wait_s": round(waited, 6),
            })
            if req.trace is not None:
                # Step point of the request's cross-process flow; binds
                # to the slice enclosing this instant (ISSUE 16).
                obs.flow("t", obs.flow_id(req.trace[0]))
        if obs.REQLOG.enabled:
            obs.REQLOG.open(
                req.uid,
                trace_id=req.trace[0] if req.trace else "",
                span_id=obs.new_span_id(),
                parent_span_id=req.trace[1] if req.trace else "",
                prompt_tokens=len(req.prompt),
                prefix_hit_tokens=matched,
                arrival_tick=req.arrival_tick,
                admit_tick=tick,
                queue_wait_s=waited,
                nblocks=self._slot_nblocks[slot] if self._paged else 0,
            )
            if self._adm_restored or self._adm_demoted:
                obs.REQLOG.note(req.uid,
                                host_restores=self._adm_restored,
                                host_demotes=self._adm_demoted)
        self._admitting = False
        if self.admission == "chunked":
            self._prefill_pos[slot] = matched
            self._slot_state[slot] = "prefill"
            self._prefill_fifo.append(slot)
        else:
            self._admit_whole(req, slot, matched)
            # First token parked in the device token vector; the slot sits
            # out this tick's step (n=0 holds it) and goes live when the
            # per-tick batched fetch reads it — no per-admit host sync.
            self._slot_state[slot] = "await"
        if obs.REGISTRY.enabled:
            _QUEUE_WAIT.observe(waited)
        return waited

    def _prefix_admit(self, req: Request, slot: int, tick: int) -> int:
        """Match the prompt against the radix tree; on a hit, dispatch the
        ONE donated pool gather (into the batch slot, or into the staging
        cache under int8 — pool rows are exact and int8 slots re-quantize
        at final chunk). Pins the matched path until retire. Returns the
        matched token count (0 when disabled or cold)."""
        if self._prefix is None:
            return 0
        matched, nodes = self._prefix.match(self._prompt_np[slot])
        self._slot_nodes[slot] = nodes
        if not matched:
            return 0
        if self.quantize:
            self._staging = self._prefix.copy_into(
                self._staging, 0, nodes, matched
            )
        else:
            self.cache = self._prefix.copy_into(
                self.cache, slot, nodes, matched
            )
        moved = matched * self._kv_token_bytes  # the gather's device bytes
        self._hit_bytes_moved += moved
        self._tick_prefix_hits += 1
        self._tick_prefix_reused += matched
        if obs.TRACER.active:
            obs.instant("prefix_hit", cat="serving", args={
                "rid": req.uid, "slot": slot, "tick": tick,
                "matched_tokens": matched,
                "prompt_len": len(req.prompt),
                "bytes_moved": moved,
            })
        return matched

    def _restore_demoted(self, slot: int, nodes: List[Any]) -> int:
        """Bring a pinned path's host-tier nodes back onto the device:
        still-pending demotions cancel in place (zero copies); flushed
        ones take fresh device blocks from the slot's reservation and
        land in ONE batched H2D scatter. Returns how many blocks were
        restored (either arc — each was a device-capacity miss the host
        tier absorbed)."""
        demoted = self._prefix.demoted_in(nodes)
        if not demoted:
            return 0

        def take_one() -> int:
            assert self._slot_reserve[slot] > 0, (
                f"slot {slot} restore outgrew its block reservation"
            )
            bid = self._pool.alloc()
            self._slot_reserve[slot] -= 1
            return bid

        rows, bids = self._prefix.restore_nodes(demoted, take_one)
        if rows:
            hp = self._host_pool
            staged = hp.read(rows)
            nb = _bucket(len(bids), self._npb, floor=1)
            ids = np.full((nb,), self.kv_blocks, np.int32)  # pad: dropped
            ids[:len(bids)] = bids

            def pad(a: np.ndarray) -> jax.Array:
                out = np.zeros((nb,) + a.shape[1:], a.dtype)
                out[:len(rows)] = a
                return jnp.asarray(out)

            if self.quantize:
                hk, hv, hks, hvs = staged
                k, v, ks, vs = self._restore_scatter(
                    self.cache.k, self.cache.v, jnp.asarray(ids),
                    pad(hk), pad(hv), self.cache.k_scale,
                    self.cache.v_scale, pad(hks), pad(hvs),
                )
                self.cache = dataclasses.replace(
                    self.cache, k=k, v=v, k_scale=ks, v_scale=vs
                )
            else:
                hk, hv = staged
                k, v = self._restore_scatter(
                    self.cache.k, self.cache.v, jnp.asarray(ids),
                    pad(hk), pad(hv),
                )
                self.cache = dataclasses.replace(self.cache, k=k, v=v)
            for row in rows:
                hp.release(row, restored=True)
        return len(demoted)

    def _paged_hit(self, req: Request, slot: int, tick: int,
                   resv: Tuple[int, List[Any], int, int]) -> int:
        """The reference-in-place hit (paged serving): write the matched
        path's pool ids into the slot's table row and set the prefill
        start — pure host bookkeeping, ZERO device KV bytes moved on the
        exact tier (``bytes_moved=0`` on the instant is the measured
        claim, not a slogan: the device sees nothing until the next
        dispatch ships the updated int32 table). Demoted path nodes
        restore FIRST (one batched H2D scatter; their bytes are the
        restore cost, amortized into the admission like the suffix's
        chunks). int8 hits additionally dequant-gather the matched
        blocks into the staging cache — the suffix's exact staged
        prefill attends them as activations-grade rows — and THOSE are
        the bytes the instant reports for int8."""
        matched, nodes, _, _ = resv
        self._slot_nodes[slot] = nodes
        if not matched:
            return 0
        restored = 0
        if self._host_pool is not None:
            restored = self._restore_demoted(slot, nodes)
            self._tick_restored += restored
            self._adm_restored += restored
        for j, node in enumerate(nodes):
            self._host_table[slot, j] = node.block_id
        self._slot_nblocks[slot] = matched // self.kv_block
        self._table_dirty = True
        moved = 0
        if self.quantize:
            # Dequantize the matched int8 blocks into staging slot 0 so
            # the suffix's staged chunks see the prefix. One jitted
            # donated gather; re-quantizing at final chunk reproduces
            # the shared blocks' bytes exactly, so they are never
            # rewritten (paged_insert_slot's ``lo``). The bucket cap is
            # FLOOR-div (the staged window nb*kv_block must fit inside
            # the staging cache — ceil would overhang a cache_len that
            # is not block-divisible; same rule as PrefixCache's
            # _nb_bucket); a matched path is at most
            # (cache_len - 1) // kv_block blocks, so the cap holds.
            nb = _bucket(len(nodes), self.cache_len // self.kv_block,
                         floor=1)
            ids = np.zeros((nb,), np.int32)
            ids[:len(nodes)] = [n.block_id for n in nodes]
            self._staging = self._dequant_hit(
                self._staging, self.cache.k, self.cache.v,
                self.cache.k_scale, self.cache.v_scale,
                jnp.asarray(ids), jnp.int32(matched),
            )
            moved = matched * self._kv_token_bytes_q
            self._hit_bytes_moved += moved
        self._tick_prefix_hits += 1
        self._tick_prefix_reused += matched
        if obs.TRACER.active:
            obs.instant("prefix_hit", cat="serving", args={
                "rid": req.uid, "slot": slot, "tick": tick,
                "matched_tokens": matched,
                "prompt_len": len(req.prompt),
                "bytes_moved": moved,
                **({"restored_blocks": restored}
                   if self._host_pool is not None else {}),
            })
        return matched

    def _publish_prefix(self, slot: int) -> None:
        """At final-chunk completion: put the prompt's full blocks into
        the pool and swap the slot's pinned path for the published one.

        Paged exact serving publishes by ADOPTION — ownership of the
        slot's private prompt blocks moves to the radix tree through the
        allocator's ledger, the KV bytes stay exactly where the prefill
        scattered them, and the slot keeps reading them through its
        unchanged table (zero device work). The contiguous and int8
        paths keep the PR-5 donated scatter — reading exact rows from
        the batch cache slot, or from the staging cache under int8
        (whose rows ARE the exact prefill, pre-quantization)."""
        if self._prefix is None:
            return
        if self._paged_prefix:
            prompt = self._prompt_np[slot]
            nb_full = len(prompt) // self.kv_block
            private = self._slot_private[slot]
            phys = {
                j: int(self._host_table[slot, j]) for j in range(nb_full)
                if int(self._host_table[slot, j]) in private
            }
            path, adopted = self._prefix.adopt(
                prompt, phys, self._slot_nodes[slot]
            )
            for j in adopted:
                private.discard(int(self._host_table[slot, j]))
            # The admit-time pins carried over into ``path`` (plus the
            # freshly created nodes); retire releases them all at once.
            self._slot_nodes[slot] = path
            return
        path, new_ids, start = self._prefix.insert(self._prompt_np[slot])
        if new_ids:
            if self.quantize:
                self._prefix.publish_from(self._staging, 0, new_ids, start)
            else:
                self._prefix.publish_from(self.cache, slot, new_ids, start)
        # Insert re-pinned the full path; only then drop the admit-time
        # refs (a transiently ref-0 matched node could otherwise be
        # evicted by the insert's own allocations).
        self._prefix.release(self._slot_nodes[slot])
        self._slot_nodes[slot] = path

    def _admit_whole(self, req: Request, slot: int, matched: int = 0) -> None:
        """Blocking admission: the whole remaining prompt prefills before
        the admit returns (the slot parks in ``await`` either way).

        Three shapes:

        - cold, exact (the legacy path): whole-prompt prefill on a
          bucket-sized mini cache, then insert into the slot's region;
        - prefix hit, exact: the gather already placed ``matched`` tokens
          in the slot, so only the suffix runs — synchronous single-slot
          chunks through a mixed-step-shaped program (one compile per
          chunk bucket, same bounded set as the tick's; other slots ride
          inert);
        - int8 with the prefix cache on (hit or cold): the staged path
          runs to completion synchronously — exact chunks into the
          staging cache, quantize + insert at the final chunk — because
          both the hit gather and the publish need exact staged rows.
        """
        plen = len(req.prompt)
        if self.quantize and self._prefix is not None:
            self._prefill_pos[slot] = matched
            pos = matched
            while pos < plen:
                n = min(self.prefill_chunk, plen - pos)
                self._run_staged_chunk(slot, n, pos + n == plen)
                pos += n  # the final chunk published from staging
            return
        if matched:
            self._prefill_pos[slot] = matched
            pos = matched
            while pos < plen:
                n = min(self.prefill_chunk, plen - pos)
                last = pos + n == plen
                self._ensure_blocks(slot, pos + n)
                rows, first = self._consume_chunk(slot, n, last)
                tq = self._chunk_bucket(n)
                # Same no-per-admit-alloc discipline as the cold path's
                # scratch below, keyed by (1, tq) row shape.
                pad = self._whole_scratch.get(tq)
                if pad is None:
                    pad = self._whole_scratch[tq] = np.zeros((1, tq),
                                                             np.int32)
                else:
                    pad[0, n:] = 0
                pad[0, :n] = rows
                self._sync_table()
                self.tok, self._lp, _, last_dev, \
                    self.cache = self._whole_suffix(
                        self.params, jnp.asarray(pad[0]), jnp.int32(slot),
                        jnp.int32(n), jnp.asarray(last), jnp.asarray(first),
                        jnp.int32(self._prefill_start[slot]), self.cache,
                        self.tok, self._keys, jnp.asarray(self._temp_np),
                        jnp.asarray(self._topk_np),
                        jnp.zeros((self.slots,), jnp.int32), self._lp,
                    )
                if last and req.uid in self._families:
                    self._slot_logits[slot] = last_dev[slot]
                pos += n
            self._publish_prefix(slot)
            return
        self._ensure_blocks(slot, plen)
        bucket = _bucket(plen, self.cache_len, multiple=self._seq_shards)
        # Reusable per-bucket scratch: zero the tail a longer previous
        # occupant may have left, then lay the prompt in — jnp.asarray
        # copies to a fresh device buffer, so immediate reuse is safe.
        padded = self._whole_scratch.get(bucket)
        if padded is None:
            padded = self._whole_scratch[bucket] = np.zeros((1, bucket),
                                                            np.int32)
        else:
            padded[0, plen:] = 0
        padded[0, :plen] = np.asarray(req.prompt, np.int32)
        payload, last_row = self._prefill(
            self.params, jnp.asarray(padded), jnp.int32(plen),
            self._keys[slot], jnp.float32(self._temp_np[slot]),
            jnp.int32(self._topk_np[slot]),
        )
        if req.uid in self._families:
            self._slot_logits[slot] = last_row[0]
        self._sync_table()
        self.cache, self.tok, self._lp = self._insert(
            self.cache, self.tok, self._lp, jnp.int32(slot), payload, plen
        )
        if self._prefix is not None:
            self._publish_prefix(slot)

    def _plan_chunks(
        self, max_n: Optional[int] = None
    ) -> List[Tuple[int, int, bool]]:
        """Sarathi-style budget pass: FIFO over prefilling slots, each
        taking up to a chunk, the tick taking at most ``prefill_budget``
        prompt tokens total. ``max_n`` clamps the per-slot chunk below
        the configured size — ticks that carry a token-tree sibling
        bundle (ISSUE 20) must keep Tq within the int32 tree-bitmask
        limit, so their chunks shrink to fit. Returns (slot, n,
        is_final) triples."""
        plan: List[Tuple[int, int, bool]] = []
        budget = self.prefill_budget
        chunk = self.prefill_chunk
        if max_n is not None:
            chunk = min(chunk, max_n)
        for slot in self._prefill_fifo:
            if budget <= 0:
                break
            plen = len(self._slot_req[slot].prompt)
            pos = self._prefill_pos[slot]
            n = min(chunk, plen - pos, budget)
            if n <= 0:
                continue
            budget -= n
            plan.append((slot, n, pos + n == plen))
        return plan

    # -- copy-on-write forking (ISSUE 15) ---------------------------------

    def _admit_family(self, req: Request, parent_slot: int,
                      free: List[int], resv) -> None:
        """Reserve the rest of an n>1 / best-of-n family at admission:
        one ``fpend`` slot per sibling (taken NOW so two half-admitted
        families can never deadlock waiting on each other's slots) and
        the block hold ``_paged_reserve`` already took. The siblings
        fork in the awaits pass, the tick the parent's first token
        lands."""
        branches = self._branches(req)
        _, _, _, fam_extra = resv
        sibs = [free.pop(0) for _ in range(branches - 1)]
        for s in sibs:
            self._slot_state[s] = "fpend"
        self._families[req.uid] = _ForkFamily(
            req=req, parent_slot=parent_slot, sibling_slots=sibs,
            sib_reserve=fam_extra // (branches - 1), hold=fam_extra,
            best_of=bool(req.best_of and req.best_of > 1),
            branches=branches,
        )
        self._uid_next_index[req.uid] = branches

    def _fork_family(self, fam: _ForkFamily, parent_slot: int,
                     tick: int, now2: float, results) -> int:
        """Fork every reserved sibling off the freshly-live parent —
        called from the awaits pass BEFORE the parent's EOS check, so
        even a one-token parent yields n independent samples. Each
        sibling's block budget moves from the family hold to the slot's
        reservation ledger; each sibling's first token (sampled by
        ``_sibling_first`` from the parent's exact prompt-end logits)
        surfaces through ONE extra batched fetch — a per-family
        admission-time cost, not a per-tick one — and the sibling goes
        live exactly like a final-chunk slot, EOS/budget checks
        included. Returns the number of first tokens emitted."""
        req = fam.req
        for j, child in enumerate(fam.sibling_slots):
            self._fork_child(parent_slot, child, 1 + j, [],
                             fam.sib_reserve, tick)
            fam.hold -= fam.sib_reserve
        fam.forked = True
        self._slot_logits[parent_slot] = None
        tok_h = np.asarray(self.tok)
        lp_h = np.asarray(self._lp)
        th = np.array(self._tok_host)
        emitted = 0
        for j, child in enumerate(fam.sibling_slots):
            t0 = int(tok_h[child])
            th[child] = t0
            self._slot_tokens[child] = [t0]
            self._slot_cum_lp[child] = float(lp_h[child])
            self._slot_state[child] = "live"
            self._slot_clen[child] = len(self._prompt_np[child])
            self._push_token(req, t0, 1 + j)
            _, vis = self._slot_admit[child]
            self._slot_ttft[child] = max(now2 - vis, 0.0)
            self._last_tok_t[child] = now2
            emitted += 1
            self.slo.observe_ttft(self._slot_ttft[child])
            if obs.REGISTRY.enabled:
                _TOKENS.inc()
                _TTFT.observe(self._slot_ttft[child])
            if obs.TRACER.active:
                obs.instant("first_token", cat="serving", args={
                    "rid": req.uid, "slot": child, "tick": tick,
                    "index": 1 + j,
                    "ttft_s": round(self._slot_ttft[child], 6),
                })
            if req.eos_id is not None and t0 == req.eos_id:
                self._retire(child, tick, OUTCOME_EOS, results)
            elif req.max_new_tokens <= 1:
                self._retire(child, tick, OUTCOME_BUDGET, results)
        self._tok_host = th
        return emitted

    def _fork_child(self, parent_slot: int, child_slot: int, index: int,
                    tokens_prefix: List[int], resv_blocks: int,
                    tick: int) -> None:
        """THE copy-on-write fork (vLLM's fork over PagedAttention block
        tables, arXiv:2309.06180): give ``child_slot`` the parent's
        history up to the fork point by SHARING every full ancestor
        block — radix-cached ancestors get one more pin, parent-private
        ones refcount into the allocator's ``shared`` state — and
        copying ONLY the partial tail block (one jitted dispatch; none
        when the fork point is block-aligned). Two flavors, both exact:

        - admission sibling (``tokens_prefix == []``): fork point = the
          prompt end. The child's first token samples from the parent's
          STASHED prompt-end logits under the child's own key (branch
          ``index`` folded in) — bit-identical inputs to the parent's
          own sample, so greedy siblings match an independent admission
          token-for-token — and parks in the device vectors; the child
          rides the existing ``await`` machinery from there. No KV row
          is ever recomputed.
        - mid-generation branch (``tokens_prefix`` = the parent's
          emitted stream): fork point = the last COMMITTED row; the
          shared tip token is re-consumed by parent and child alike,
          each writing its own FRESH copy of that row, and the child's
          next sample (its own key, stream index ``len(tokens_prefix)``)
          diverges.

        ``resv_blocks`` is the child's worst-case NEW-block budget,
        already reserved by the caller."""
        req = self._slot_req[parent_slot]
        prompt = self._prompt_np[parent_slot]
        plen = len(prompt)
        if tokens_prefix:
            tip = int(tokens_prefix[-1])
            L = plen + len(tokens_prefix) - 1
        else:
            tip = 0  # placeholder; _sibling_first parks the real token
            L = plen
        B = self.kv_block
        nshare = L // B
        # Shared ancestors, radix tier first: the child pins the
        # parent's matched/published path like a second admission.
        pnodes = self._slot_nodes[parent_slot]
        n_pin = min(nshare, len(pnodes))
        if n_pin:
            child_nodes = self._prefix.repin(pnodes[:n_pin])
            self._slot_nodes[child_slot] = child_nodes
        else:
            self._slot_nodes[child_slot] = []
        # ...then CoW-refcount the rest (parent-private decode blocks,
        # or unpublished prompt blocks when the prefix cache is off).
        share_bids = [int(self._host_table[parent_slot, j])
                      for j in range(n_pin, nshare)]
        self._slot_shared[child_slot] = set(
            self._pool.fork_shared(share_bids)
        )
        for bid in share_bids:
            self._slot_private[parent_slot].discard(bid)
            self._slot_shared[parent_slot].add(bid)
        self._host_table[child_slot, :nshare] = \
            self._host_table[parent_slot, :nshare]
        self._host_table[child_slot, nshare:] = 0
        self._slot_reserve[child_slot] = resv_blocks
        self._slot_private[child_slot] = set()
        self._slot_nblocks[child_slot] = nshare
        need_copy = (L % B) != 0
        if need_copy:
            src = int(self._host_table[parent_slot, nshare])
            assert self._slot_reserve[child_slot] > 0, (
                f"fork into slot {child_slot} outgrew its reservation"
            )
            dst = self._pool.alloc()
            self._slot_reserve[child_slot] -= 1
            self._host_table[child_slot, nshare] = dst
            self._slot_private[child_slot].add(dst)
            self._slot_nblocks[child_slot] = nshare + 1
        else:
            src = dst = 0  # block-aligned fork: the copy degenerates to
            # a self-write and the program only parks the tip
        self._table_dirty = True
        self.cache, self.tok = self._fork_copy(
            self.cache, self.tok, jnp.int32(src), jnp.int32(dst),
            jnp.int32(child_slot), jnp.int32(tip),
        )
        salt = (req.seed if req.seed is not None else req.uid) & 0x7FFFFFFF
        self._salt_np[child_slot] = salt
        self._keys = self._seed_key(self._keys, jnp.int32(child_slot),
                                    jnp.int32(salt), jnp.int32(index))
        # Host mirrors: the child is an ordinary live slot from here on.
        self._slot_req[child_slot] = req
        self._slot_index[child_slot] = index
        self._slot_tokens[child_slot] = list(tokens_prefix)
        self._prompt_np[child_slot] = prompt
        self._slot_admit[child_slot] = self._slot_admit[parent_slot]
        self._slot_wait[child_slot] = self._slot_wait[parent_slot]
        self._slot_ttft[child_slot] = (
            self._slot_ttft[parent_slot] if tokens_prefix else 0.0
        )
        self._slot_max_tbt[child_slot] = 0.0
        self._slot_prefix_hit[child_slot] = nshare * B
        self._slot_cum_lp[child_slot] = (
            self._slot_cum_lp[parent_slot] if tokens_prefix else 0.0
        )
        self._temp_np[child_slot] = self._temp_np[parent_slot]
        self._topk_np[child_slot] = self._topk_np[parent_slot]
        self._prefill_start[child_slot] = 0
        self._chunk_k[child_slot] = 0
        self._slot_clen[child_slot] = L
        self._live_reset[child_slot] = L
        if tokens_prefix:
            # Mid-generation branch: an ordinary live slot whose next
            # tick re-consumes the shared tip (a fresh row for each
            # branch) — park the tip host- and device-side.
            self._slot_state[child_slot] = "live"
            self._last_tok_t[child_slot] = self._last_tok_t[parent_slot]
            th = np.array(self._tok_host)  # the fetch view is read-only
            th[child_slot] = tip
            self._tok_host = th
        else:
            # Admission sibling: draw the child's own first token from
            # the parent's stashed prompt-end logits (exactly what an
            # independent admission's prefill would sample from) and
            # ride the await machinery — its TTFT closes at the next
            # batched fetch, like any final-chunk slot.
            row = self._slot_logits[parent_slot]
            assert row is not None, "fork family lost its logits stash"
            self.tok, self._lp = self._sibling_first(
                self.tok, self._lp, row, self._keys[child_slot],
                jnp.float32(self._temp_np[child_slot]),
                jnp.int32(self._topk_np[child_slot]),
                jnp.int32(child_slot),
            )
            self._slot_state[child_slot] = "await"
            self._last_tok_t[child_slot] = self._last_tok_t[parent_slot]
        self._slot_span[child_slot] = obs.span(
            f"request:{req.uid}", cat="serving",
            args=None if not obs.TRACER.active else {
                "rid": req.uid, "slot": child_slot, "admit_tick": tick,
                "prompt_len": len(prompt), "index": index,
                "fork_of_slot": parent_slot,
            },
        )
        self._forks_life += 1
        self._fork_shared_life += nshare
        self._tick_forks += 1
        self._tick_fork_shared += nshare
        if obs.REGISTRY.enabled:
            _FORKS.inc()
            if nshare:
                _FORK_SHARED.inc(nshare)
        if obs.TRACER.active:
            obs.instant("fork", cat="serving", args={
                "rid": req.uid, "tick": tick,
                "parent_slot": parent_slot, "child_slot": child_slot,
                "index": index, "shared_blocks": nshare,
                "copied_blocks": int(need_copy),
                "at_tokens": len(tokens_prefix),
            })
        if obs.REQLOG.enabled and nshare:
            obs.REQLOG.note(req.uid, fork_shared_blocks=nshare)

    def _fork_live(self, uid: int, tick: int,
                   pend_uids: Set[int]) -> str:
        """One mailboxed fork(uid): branch the request's lowest-index
        live slot onto a free slot. Returns ``"done"`` (forked, or a
        no-op for an unknown/finished uid), ``"wait"`` (the request
        exists but is not live yet — queued/prefilling/awaiting; the
        carry keeps the fork pending without burning retries), or
        ``"retry"`` (slot/block scarcity — bounded retries, then the
        fork expires)."""
        if self._speculate or not self._paged:
            log.warning(
                "fork(%d) ignored: forking needs a paged, "
                "non-speculative engine", uid,
            )
            return "done"
        parent = None
        for i, rq in enumerate(self._slot_req):
            if rq is None or rq.uid != uid:
                continue
            if self._slot_state[i] == "live":
                if parent is None \
                        or self._slot_index[i] < self._slot_index[parent]:
                    parent = i
            else:
                return "wait"  # still prefilling/awaiting — not yet
                # forkable; the carry holds until it goes live
        if parent is None:
            return "done" if uid not in pend_uids else "wait"
        req = self._slot_req[parent]
        toks = self._slot_tokens[parent]
        if len(toks) >= req.max_new_tokens:
            return "done"  # retiring this tick; nothing left to branch
        if parent in self._tree_fams:
            log.warning(
                "fork(%d) ignored: the slot already decodes a token "
                "tree (one conversion per request)", uid,
            )
            return "done"
        t = len(toks)
        if (self._tree_sampling and self._tree_ok and self._fork_ok
                and req.uid not in self._families
                and 2 * (req.max_new_tokens - t) <= self._spec_rows_cap
                and len(self._prompt_np[parent]) + t - 1
                + 2 * (req.max_new_tokens - t) <= self.cache_len):
            # Tree conversion: both continuations share the slot — zero
            # new slots, zero copied blocks (the partial tail block is
            # shared too; the tip re-enters as a replayed suffix row).
            return self._tree_convert_live(parent, uid, tick)
        free = self._free_slots()
        if not free:
            return "retry"
        L = len(self._prompt_np[parent]) + max(len(toks) - 1, 0)
        need = -(-(len(req.prompt) + req.max_new_tokens)
                 // self.kv_block) - L // self.kv_block
        if not self._pool.reserve(need):
            return "retry"
        idx = self._uid_next_index.get(uid, self._branches(req))
        self._uid_next_index[uid] = idx + 1
        self._fork_child(parent, free[0], idx, list(toks), need, tick)
        return "done"

    def _apply_forks(self, forks: List[int], tick: int,
                     pending) -> None:
        """The control sweep's fork arc: apply mailboxed fork(uid)s and
        re-attempt deferred ones. A fork whose request exists but is
        not live yet (queued / prefilling — "wait") stays carried at
        full TTL until the request goes live; slot/block scarcity
        ("retry") burns one of 3 retries per sweep, then the fork
        expires; genuinely unknown uids age out as no-ops."""
        for uid in forks:
            if uid not in self._fork_carry:
                self._fork_carry[uid] = 3
        pend_uids = {r.uid for r in pending}
        for uid in list(self._fork_carry):
            verdict = self._fork_live(uid, tick, pend_uids)
            if verdict == "done":
                self._fork_carry.pop(uid, None)
            elif verdict == "wait":
                self._fork_carry[uid] = 3  # still coming; keep waiting
            else:
                self._fork_carry[uid] -= 1
                if self._fork_carry[uid] <= 0:
                    del self._fork_carry[uid]
                    log.warning(
                        "fork(%d) expired unserved (slots/blocks "
                        "stayed scarce)", uid,
                    )

    def _family_branch_done(self, fam: _ForkFamily,
                            result: RequestResult) -> None:
        """A branch retired: collect it; the LAST branch completes the
        family ('join') — best-of-n selects and streams its winner."""
        fam.done.append(result)
        if len(fam.done) >= fam.branches:
            self._families.pop(fam.req.uid, None)
            if fam.best_of:
                self._emit_best_of(fam)

    def _cancel_unforked(self, fam: _ForkFamily, parent_result:
                         RequestResult, tick: int, results) -> None:
        """The parent retired BEFORE its first token (cancel/deadline
        mid-prefill): the siblings never forked — free their fpend
        slots, return the family's block hold, and finish each sibling
        unserved with the parent's outcome (one result per requested
        completion, so clients counting n finishes always converge)."""
        if fam.tree:
            # Tree families hold no sibling slots and no family hold —
            # the whole worst case is the parent slot's reservation,
            # already freed by the retire. Only the per-branch results
            # need synthesizing.
            for j in range(1, fam.branches):
                res = dataclasses.replace(
                    parent_result, index=j, tokens=[], cum_logprob=0.0,
                    ttft_s=0.0,
                )
                results.append(res)
                fam.done.append(res)
                if parent_result.outcome in (OUTCOME_DEADLINE,
                                             OUTCOME_SHED,
                                             OUTCOME_ERROR):
                    self.slo.observe_miss()
                if obs.REGISTRY.enabled:
                    _REQUESTS.labels(outcome=res.outcome).inc()
                self._notify_finish(fam.req, res, fam)
            return
        if fam.hold:
            self._pool.unreserve(fam.hold)
            fam.hold = 0
        for j, s in enumerate(fam.sibling_slots):
            self._slot_state[s] = "free"
            res = dataclasses.replace(
                parent_result, index=1 + j, tokens=[], cum_logprob=0.0,
                ttft_s=0.0,
            )
            results.append(res)
            fam.done.append(res)
            if parent_result.outcome in (OUTCOME_DEADLINE, OUTCOME_SHED,
                                         OUTCOME_ERROR):
                self.slo.observe_miss()
            if obs.REGISTRY.enabled:
                _REQUESTS.labels(outcome=res.outcome).inc()
            self._notify_finish(fam.req, res, fam)
        fam.sibling_slots = []

    def _emit_best_of(self, fam: _ForkFamily) -> None:
        """Best-of-n join: pick the winner by cumulative logprob (ties
        break to the lowest branch index) among cleanly finished
        branches — every branch failed means the parent's result stands
        — and stream it NOW as index 0 (per-branch streaming was held
        back; the winner was unknowable until the family drained)."""
        req = fam.req
        happy = [r for r in fam.done
                 if r.outcome in (OUTCOME_EOS, OUTCOME_BUDGET)]
        pool = happy or fam.done
        winner = max(pool, key=lambda r: (r.cum_logprob, -r.index))
        if obs.TRACER.active:
            obs.instant("best_of_selected", cat="serving", args={
                "rid": req.uid, "index": winner.index,
                "cum_logprob": round(winner.cum_logprob, 6),
                "branches": len(fam.done),
            })
        out = dataclasses.replace(winner, index=0)
        for t in winner.tokens:
            self._deliver_token(req, 0, t)
        self._deliver_finish(req, 0, out)

    # -- token-tree sibling decode (ISSUE 20) -----------------------------
    #
    # The family's k branches decode in ONE slot as one verify-shaped
    # row bundle per tick. The device cache freezes at ``base_len``
    # committed rows (the shared ancestor path — prompt, or prompt +
    # shared generated prefix for a mid-generation conversion); every
    # live branch's divergent suffix is REPLAYED into the window
    # [base_len, base_len + k*s) each tick under tree_mask/positions,
    # so suffix rows attend only to their own branch plus the frozen
    # ancestors. Each branch's last row draws its next token under the
    # fork-slot path's exact key chain — token-identical layouts. A
    # retiring branch shrinks the window the same tick; the last two
    # transitions are collapse (k=1: compact the survivor's suffix
    # contiguous via compact_decode_window and hand the slot back to
    # the plain decode path) and close (k=0: free the slot).

    def _admit_tree_family(self, req: Request, slot: int) -> None:
        """Register an n>1 / best-of-n family that will decode as a
        token tree in ``slot`` (reservation already taken tree-shaped
        by ``_paged_reserve``). Branches materialize at the awaits
        pass, the tick the parent's first token lands."""
        branches = self._branches(req)
        fam = _ForkFamily(
            req=req, parent_slot=slot, sibling_slots=[], sib_reserve=0,
            hold=0, best_of=bool(req.best_of and req.best_of > 1),
            branches=branches, tree=True,
            base_len=len(req.prompt), fork_len=1,
        )
        self._families[req.uid] = fam
        self._tree_fams[slot] = fam
        self._uid_next_index[req.uid] = branches
        self._tree_fams_life += 1

    def _tree_family_start(self, fam: _ForkFamily, slot: int,
                           first: int, tick: int, now2: float,
                           results) -> int:
        """Branch the freshly-live parent into its k tree siblings —
        called from the awaits pass BEFORE any EOS check, so even a
        one-token parent yields k independent samples. Siblings' first
        tokens draw from the parent's STASHED prompt-end logits under
        their own branch keys (ONE tiny dispatch + one small fetch per
        family, not per tick) — bit-identical to the fork-slot path's
        ``_sibling_first`` draws. Every branch's first token then runs
        its own EOS/budget check here, branch 0 included (the caller
        skips its generic check). Returns sibling tokens emitted."""
        req = fam.req
        fam.forked = True
        k = fam.branches
        row = self._slot_logits[slot]
        assert row is not None, "tree family lost its logits stash"
        self._slot_logits[slot] = None
        bix = np.arange(1, k, dtype=np.int32)
        tok_d, lp_d = self._tree_first(
            row, jnp.asarray(bix), jnp.int32(self._salt_np[slot]),
            jnp.float32(self._temp_np[slot]),
            jnp.int32(self._topk_np[slot]),
        )
        tok_h = np.asarray(tok_d)
        lp_h = np.asarray(lp_d)
        fam.br_tokens = [self._slot_tokens[slot]] + [
            [int(tok_h[j])] for j in range(k - 1)
        ]
        fam.br_cum_lp = [self._slot_cum_lp[slot]] + [
            float(lp_h[j]) for j in range(k - 1)
        ]
        fam.br_live = [True] * k
        fam.br_index = list(range(k))
        fam.br_ttft = [self._slot_ttft[slot]] * k
        emitted = 0
        dead: List[Tuple[int, str]] = []
        for b in range(k):
            t0 = int(fam.br_tokens[b][0])
            if b > 0:
                self._push_token(req, t0, b)
                emitted += 1
                self.slo.observe_ttft(fam.br_ttft[b])
                if obs.REGISTRY.enabled:
                    _TOKENS.inc()
                    _TTFT.observe(fam.br_ttft[b])
                if obs.TRACER.active:
                    obs.instant("first_token", cat="serving", args={
                        "rid": req.uid, "slot": slot, "tick": tick,
                        "index": b, "tree": True,
                        "ttft_s": round(fam.br_ttft[b], 6),
                    })
            if req.eos_id is not None and t0 == req.eos_id:
                dead.append((b, OUTCOME_EOS))
            elif req.max_new_tokens <= 1:
                dead.append((b, OUTCOME_BUDGET))
        self._forks_life += k - 1
        self._tick_forks += k - 1
        nshare = fam.base_len // self.kv_block
        self._fork_shared_life += (k - 1) * nshare
        self._tick_fork_shared += (k - 1) * nshare
        if obs.REGISTRY.enabled:
            _FORKS.inc(k - 1)
            if nshare:
                _FORK_SHARED.inc((k - 1) * nshare)
        if obs.TRACER.active:
            # One instant per sibling — the fork-slot path's exact
            # trace shape, so family post-mortems read identically
            # whichever layout served them.
            for b in range(1, k):
                obs.instant("fork", cat="serving", args={
                    "rid": req.uid, "tick": tick, "parent_slot": slot,
                    "child_slot": slot, "index": b, "tree": True,
                    "shared_blocks": nshare, "copied_blocks": 0,
                    "at_tokens": 0,
                })
        for b, outcome in dead:
            self._tree_finish_branch(slot, fam, b, outcome, tick, now2,
                                     results)
        self._tree_settle(slot, fam, 0, [], bool(dead), tick)
        return emitted

    def _pack_tree(
        self, fam: _ForkFamily
    ) -> Tuple[PackedSpec, List[int], int]:
        """This tick's sibling bundle: every live branch's divergent
        suffix (its tokens past the frozen ancestor rows), packed
        branch-major. All live suffixes have equal length — each branch
        gains exactly one token per tick. Returns (pack, the live
        branch ids in packed order, the suffix length)."""
        d = fam.fork_len - 1
        order = [b for b in range(fam.branches) if fam.br_live[b]]
        suffixes = [fam.br_tokens[b][d:] for b in order]
        return pack_siblings(suffixes), order, len(suffixes[0])

    def _tree_commit_all(
        self,
        tree_plan: Dict[int, Tuple[PackedSpec, List[int], int]],
        alltok: np.ndarray,
        alllp: np.ndarray,
        now: float,
        tick: int,
        results: List[RequestResult],
        tbt: List[float],
    ) -> int:
        """The host half of a tree-sibling tick: each live branch's next
        token is the draw at its LAST packed row (rows before it
        re-drew the branch's existing suffix tokens — same keys, same
        logits, bit-identical, discarded). EOS/budget checks run per
        branch; retires shrink the family the same tick (trim /
        collapse / close). Returns tokens emitted."""
        emitted_total = 0
        for slot, (pack, order, s) in tree_plan.items():
            fam = self._tree_fams.get(slot)
            if fam is None:
                continue
            req = fam.req
            self._tick_tree_branches += len(order)
            self._tree_branches_life += len(order)
            gap = max(now - self._last_tok_t[slot], 0.0)
            self._last_tok_t[slot] = now
            if gap > self._slot_max_tbt[slot]:
                self._slot_max_tbt[slot] = gap
            self.slo.observe_tbt(gap)
            dead: List[Tuple[int, str]] = []
            for rank, b in enumerate(order):
                r = rank * s + s - 1
                t_new = int(alltok[slot, r])
                fam.br_tokens[b].append(t_new)
                fam.br_cum_lp[b] += float(alllp[slot, r])
                self._push_token(req, t_new, fam.br_index[b])
                emitted_total += 1
                tbt.append(gap if rank == 0 else 0.0)
                if obs.REGISTRY.enabled:
                    _TOKENS.inc()
                    _TBT.observe(gap if rank == 0 else 0.0)
                if req.eos_id is not None and t_new == req.eos_id:
                    dead.append((b, OUTCOME_EOS))
                elif len(fam.br_tokens[b]) >= req.max_new_tokens:
                    dead.append((b, OUTCOME_BUDGET))
            for b, outcome in dead:
                self._tree_finish_branch(slot, fam, b, outcome, tick,
                                         now, results)
            self._tree_settle(slot, fam, s, order, bool(dead), tick)
        return emitted_total

    def _tree_finish_branch(self, slot: int, fam: _ForkFamily, b: int,
                            outcome: str, tick: int, now: float,
                            results) -> None:
        """One tree branch leaves the family: its per-branch result is
        final NOW (tokens, cum_logprob, its own outcome); the slot's
        resources shrink in ``_tree_settle``, not here."""
        fam.br_live[b] = False
        req = fam.req
        admit_tick, visible_at = self._slot_admit[slot]
        res = RequestResult(
            uid=req.uid,
            tokens=list(fam.br_tokens[b]),
            prompt_len=len(req.prompt),
            arrival_tick=req.arrival_tick,
            admit_tick=admit_tick,
            finish_tick=tick,
            queue_wait_s=self._slot_wait[slot],
            completion_s=max(now - visible_at, 0.0),
            outcome=outcome,
            ttft_s=fam.br_ttft[b],
            prefix_hit_tokens=self._slot_prefix_hit[slot],
            index=fam.br_index[b],
            cum_logprob=fam.br_cum_lp[b],
        )
        results.append(res)
        if outcome in (OUTCOME_EOS, OUTCOME_BUDGET):
            self.slo.observe_request(fam.br_ttft[b],
                                     self._slot_max_tbt[slot])
        elif outcome in (OUTCOME_DEADLINE, OUTCOME_SHED, OUTCOME_ERROR):
            self.slo.observe_miss()
        self._tick_branch_retired += 1
        if obs.REGISTRY.enabled:
            _REQUESTS.labels(outcome=outcome).inc()
        if obs.TRACER.active:
            obs.instant("request_retired", cat="serving", args={
                "rid": req.uid, "slot": slot, "tick": tick,
                "outcome": outcome, "index": fam.br_index[b],
                "tree": True,
            })
        self._notify_finish(req, res, fam)
        self._family_branch_done(fam, res)

    def _tree_settle(self, slot: int, fam: _ForkFamily, s: int,
                     order: List[int], retired_any: bool,
                     tick: int) -> None:
        """Normalize the slot after a tree tick (or the family start):
        k live branches keep the tree (trimming the window reservation
        when some retired — the same-tick no-leak contract), one
        survivor collapses the slot back to plain decode, zero closes
        it."""
        k_live = sum(fam.br_live)
        if k_live == 0:
            self._tree_close(slot, fam, tick)
        elif k_live == 1:
            self._tree_collapse(slot, fam, s, order)
        elif retired_any:
            span = max(
                fam.base_len
                + k_live * (fam.req.max_new_tokens - fam.fork_len),
                len(fam.req.prompt) + fam.req.max_new_tokens,
            )
            self._slot_trim(slot, -(-span // self.kv_block))

    def _slot_trim(self, slot: int, need: int) -> None:
        """Shrink ``slot`` to a ``need``-block worst case the SAME tick
        its occupant got smaller: unmap private tail blocks past the
        need (their rows belonged to retired branches; host bookkeeping
        only — any in-flight gather already dispatched against the old
        table) and return the excess reservation to the pool."""
        if not self._paged:
            return
        while self._slot_nblocks[slot] > need:
            j = self._slot_nblocks[slot] - 1
            bid = int(self._host_table[slot, j])
            if bid not in self._slot_private[slot]:
                break  # shared ancestors never sit past the need
            self._pool.unmap_private(bid)
            self._slot_private[slot].discard(bid)
            self._slot_reserve[slot] += 1
            self._host_table[slot, j] = 0
            self._slot_nblocks[slot] -= 1
            self._table_dirty = True
        excess = self._slot_nblocks[slot] + self._slot_reserve[slot] \
            - need
        if excess > 0:
            give = min(excess, self._slot_reserve[slot])
            if give:
                self._pool.unreserve(give)
                self._slot_reserve[slot] -= give
                self._pool.gen += 1

    def _tree_collapse(self, slot: int, fam: _ForkFamily, s: int,
                       order: List[int]) -> None:
        """One branch left: gather its replayed suffix contiguous
        (compact_decode_window — a no-op when it already sits at rank
        0), rebind the slot's mirrors and PRNG key to the survivor's
        stream, park its tip, and hand the slot back to the plain
        decode path. The survivor continues bit-identically: its slot
        key chain equals the in-program fold it decoded under."""
        req = fam.req
        b = fam.br_live.index(True)
        if s > 0:
            rank = order.index(b)
            if rank > 0:
                w = max(self._spec_rows_cap, 1)
                src = np.tile(np.arange(w, dtype=np.int32),
                              (self.slots, 1))
                src[slot, :s] = rank * s + np.arange(s, dtype=np.int32)
                n = np.zeros((self.slots,), np.int32)
                n[slot] = s
                start = np.zeros((self.slots,), np.int32)
                start[slot] = fam.base_len
                self.cache = self._compact(
                    self.cache, jnp.asarray(start), jnp.asarray(src),
                    jnp.asarray(n),
                )
        self._slot_clen[slot] = fam.base_len + s
        self._live_reset[slot] = fam.base_len + s
        self._slot_tokens[slot] = fam.br_tokens[b]
        self._slot_index[slot] = fam.br_index[b]
        self._slot_cum_lp[slot] = fam.br_cum_lp[b]
        self._slot_ttft[slot] = fam.br_ttft[b]
        self._keys = self._seed_key(self._keys, jnp.int32(slot),
                                    jnp.int32(self._salt_np[slot]),
                                    jnp.int32(fam.br_index[b]))
        tip = int(fam.br_tokens[b][-1])
        self.cache, self.tok = self._fork_copy(
            self.cache, self.tok, jnp.int32(0), jnp.int32(0),
            jnp.int32(slot), jnp.int32(tip),
        )
        th = np.array(self._tok_host)
        th[slot] = tip
        self._tok_host = th
        self._tree_fams.pop(slot, None)
        need = -(-(len(req.prompt) + req.max_new_tokens)
                 // self.kv_block)
        self._slot_trim(slot, need)
        if obs.TRACER.active:
            obs.instant("tree_collapse", cat="serving", args={
                "rid": req.uid, "slot": slot, "index": fam.br_index[b],
                "suffix": s,
            })

    def _tree_close(self, slot: int, fam: _ForkFamily,
                    tick: int) -> None:
        """Every branch finished: close the request's span/ledger and
        free the slot — prefix pins, private blocks, CoW refs, unspent
        reservation — the same tick the last branch retired."""
        self._tree_fams.pop(slot, None)
        req = fam.req
        span = self._slot_span[slot]
        if span is not None:
            if obs.TRACER.active:
                span.set(
                    tokens=sum(len(t) for t in fam.br_tokens),
                    branches=fam.branches, tree=True,
                )
            span.__exit__(None, None, None)
            self._slot_span[slot] = None
        if obs.REQLOG.enabled:
            led = obs.REQLOG.finish(
                req.uid, outcome=fam.done[-1].outcome if fam.done
                else OUTCOME_EOS, finish_tick=tick,
                tokens_decoded=sum(len(t) for t in fam.br_tokens),
                now=time.monotonic(),
            )
            if fam.done:
                fam.done[-1].ledger = led
        self._free_slot_resources(slot)
        if not any(rq is not None and rq.uid == req.uid
                   for rq in self._slot_req):
            self._uid_next_index.pop(req.uid, None)

    def _tree_convert_live(self, parent: int, uid: int,
                           tick: int) -> str:
        """Mid-generation fork(uid) as a tree conversion: keep the live
        slot, freeze its committed rows as the shared ancestors, and
        decode both continuations as a 2-branch token tree — zero new
        slots, zero copied blocks (even the partial tail block is
        shared; both branches re-consume the tip as replayed suffix
        rows). Pure host bookkeeping plus the reservation delta."""
        req = self._slot_req[parent]
        toks = self._slot_tokens[parent]
        t = len(toks)
        plen = len(self._prompt_np[parent])
        base_len = plen + t - 1
        span = max(base_len + 2 * (req.max_new_tokens - t),
                   plen + req.max_new_tokens)
        need = -(-span // self.kv_block)
        held = self._slot_nblocks[parent] + self._slot_reserve[parent]
        delta = need - held
        if delta > 0:
            if not self._pool.reserve(delta):
                return "retry"
            self._slot_reserve[parent] += delta
        idx = self._uid_next_index.get(uid, self._branches(req))
        self._uid_next_index[uid] = idx + 1
        fam = _ForkFamily(
            req=req, parent_slot=parent, sibling_slots=[],
            sib_reserve=0, hold=0, best_of=False, branches=2,
            forked=True, tree=True, base_len=base_len, fork_len=t,
            br_tokens=[toks, list(toks)],
            br_cum_lp=[self._slot_cum_lp[parent],
                       self._slot_cum_lp[parent]],
            br_live=[True, True],
            br_index=[self._slot_index[parent], idx],
            br_ttft=[self._slot_ttft[parent], self._slot_ttft[parent]],
        )
        self._families[req.uid] = fam
        self._tree_fams[parent] = fam
        self._tree_fams_life += 1
        # Tree ticks reset the device length to base_len every dispatch;
        # a pending fork/collapse reset is subsumed.
        self._live_reset.pop(parent, None)
        self._slot_clen[parent] = base_len
        nshare = base_len // self.kv_block
        self._forks_life += 1
        self._fork_shared_life += nshare
        self._tick_forks += 1
        self._tick_fork_shared += nshare
        if obs.REGISTRY.enabled:
            _FORKS.inc()
            if nshare:
                _FORK_SHARED.inc(nshare)
        if obs.TRACER.active:
            obs.instant("fork", cat="serving", args={
                "rid": req.uid, "tick": tick, "parent_slot": parent,
                "child_slot": parent, "index": idx, "tree": True,
                "shared_blocks": nshare, "copied_blocks": 0,
                "at_tokens": t,
            })
        if obs.REQLOG.enabled and nshare:
            obs.REQLOG.note(req.uid, fork_shared_blocks=nshare)
        return "done"

    # -- speculation (ISSUE 8) --------------------------------------------

    def _spec_bucket(self, n: int) -> int:
        """Tq bucket for a verify tick: power-of-two, floor 8 (shared with
        the chunk buckets so mixtures reuse programs), capped at the
        cache-window-safe rows cap."""
        b = min(8, self._spec_rows_cap)
        while b < n:
            b *= 2
        return min(b, self._spec_rows_cap)

    def _draft_slot(self, i: int, tree_ok: bool = True) -> PackedSpec:
        """Build slot ``i``'s verify chunk for this tick: ask the drafter
        for up to the clamped budget of candidates (never past the
        request's remaining token budget — the satellite contract: a
        drafter proposing past ``max_new_tokens`` is truncated here, not
        trusted), fall back to the root-path chain where the tree mask
        cannot run (the seq-sharded contiguous topology, or a tick whose
        prefill chunks widen Tq past the int32 bitmask — ``tree_ok``),
        and pack with the committed tip as row 0. A ``None`` or empty
        proposal packs to one row — a plain decode tick."""
        req = self._slot_req[i]
        tip = self._slot_tokens[i][-1]
        remaining = req.max_new_tokens - len(self._slot_tokens[i])
        budget = min(self.draft_k, remaining - 1, self._spec_rows_cap - 1)
        prop: Optional[DraftProposal] = None
        if budget >= 1:
            hist = self._hist_buf[i, :self._hist_len[i]]  # view, no copy
            prop = self._drafter.propose(hist, budget)
        if prop is not None and len(prop) > 0:
            prop = prop.truncated(budget)
            if (not self._tree_ok or not tree_ok) and not prop.is_chain:
                prop = prop.chain_prefix()
        else:
            prop = DraftProposal(
                tokens=np.empty((0,), np.int32),
                parents=np.empty((0,), np.int32),
            )
        return pack_proposal(tip, prop)

    def _spec_unmap(self, slot: int) -> None:
        """Roll back the slot's block tail after a partial accept: blocks
        wholly past the committed coverage were only ever written with
        rejected rows — unmap them into the slot's reservation
        (free + re-reserved, so the later re-allocation cannot fail and
        rolled-back KV never leaks pool capacity). Runs AFTER the commit
        compaction dispatched (the device still maps the blocks for that
        gather; nothing allocates until the next tick's admissions)."""
        keep = -(-self._slot_clen[slot] // self.kv_block)
        while self._slot_nblocks[slot] > keep:
            j = self._slot_nblocks[slot] - 1
            bid = int(self._host_table[slot, j])
            if bid not in self._slot_private[slot]:
                # Shared (prefix) blocks never sit past the committed
                # tail; defensive stop if one ever did.
                break
            self._pool.unmap_private(bid)
            self._slot_private[slot].discard(bid)
            self._slot_reserve[slot] += 1
            self._host_table[slot, j] = 0
            self._slot_nblocks[slot] -= 1
            self._table_dirty = True

    def _spec_commit_all(
        self,
        spec_plan: Dict[int, PackedSpec],
        alltok: np.ndarray,
        alllp: np.ndarray,
        width: int,
        now: float,
        tick: int,
        results: List[RequestResult],
        tbt: List[float],
    ) -> int:
        """The host half of a verify tick: walk each slot's fetched
        per-row draws, emit the committed burst (EOS/budget checks in
        stream order — an EOS inside the burst truncates it, same tick),
        update the committed-length ledger (the next step's reset performs
        the device rollback), batch the tree compactions into ONE
        dispatch, and unmap rolled-back paged blocks. Returns the number
        of tokens emitted.

        Greedy slots walk the argmax path; sampled slots walk the
        STOCHASTIC path (Leviathan coupling, arXiv:2211.17192): each
        window row's fetched token was drawn from the target softmax
        under that row's deterministic stream key, so accepting a draft
        token iff the draw equals it emits exactly the target
        distribution — token-identical to non-speculative sampling
        under the same seed."""
        emitted_total = 0
        compact_src: Optional[np.ndarray] = None
        compact_n: Optional[np.ndarray] = None
        compact_start: Optional[np.ndarray] = None
        t_slots = t_prop = t_acc = 0
        t_ver = 0
        for i, pack in spec_plan.items():
            req = self._slot_req[i]
            if self._temp_np[i] > 0.0:
                kept, committed = accept_stochastic_path(pack, alltok[i])
                if obs.REGISTRY.enabled and pack.rows:
                    _SPEC_ACCEPT_SAMPLES.inc(pack.rows)
            else:
                kept, committed = accept_longest_path(pack, alltok[i])
            m = pack.rows - 1
            t_slots += 1
            t_prop += m
            t_acc += len(kept)
            if m:
                t_ver += 1
            # Truncate the burst at the request budget and at EOS — the
            # drafter was already clamped to the budget, but the contract
            # is enforced here, where it matters.
            remaining = req.max_new_tokens - len(self._slot_tokens[i])
            emit_list = committed[:remaining]
            outcome = None
            if req.eos_id is not None:
                for j, t in enumerate(emit_list):
                    if t == req.eos_id:
                        emit_list = emit_list[:j + 1]
                        outcome = OUTCOME_EOS
                        break
            n_emit = len(emit_list)
            if outcome is None and (
                len(self._slot_tokens[i]) + n_emit >= req.max_new_tokens
            ):
                outcome = OUTCOME_BUDGET
            # The burst lands at one instant: the first token carries the
            # whole inter-token gap, the rest arrive for free — the
            # honest latency shape of speculative decode.
            gap = max(now - self._last_tok_t[i], 0.0)
            self._last_tok_t[i] = now
            if gap > self._slot_max_tbt[i]:
                self._slot_max_tbt[i] = gap
            self.slo.observe_tbt(gap)
            hl = self._hist_len[i]
            rows_used = [0] + kept  # window row each committed token used
            for j, t in enumerate(emit_list):
                self._slot_tokens[i].append(int(t))
                self._slot_cum_lp[i] += float(alllp[i][rows_used[j]])
                self._hist_buf[i, hl + j] = int(t)
                self._push_token(req, int(t))
                tbt.append(gap if j == 0 else 0.0)
                if obs.REGISTRY.enabled:
                    _TOKENS.inc()
                    _TBT.observe(gap if j == 0 else 0.0)
            self._hist_len[i] = hl + n_emit
            emitted_total += n_emit
            if obs.REGISTRY.enabled and m:
                _SPEC_PROPOSED.inc(m)
                if kept:
                    _SPEC_ACCEPTED.inc(len(kept))
            if obs.TRACER.active:
                obs.instant("spec_verify", cat="serving", args={
                    "rid": req.uid, "slot": i, "tick": tick,
                    "proposed": m, "accepted": len(kept),
                    "committed": n_emit,
                })
            if obs.REQLOG.enabled and m:
                obs.REQLOG.note(req.uid, spec_proposed=m,
                                spec_accepted=len(kept))
            if outcome is not None:
                self._retire(i, tick, outcome, results)
                continue
            # Committed cache rows: the tip's (row 0) plus every accepted
            # draft row; the bonus token is the new pending tip.
            a = len(kept)
            old_clen = self._slot_clen[i]
            if kept != list(range(1, a + 1)):
                # A non-chain accepted path: its KV rows sit scattered in
                # the window — batch the gather-to-front for ONE compact
                # dispatch after the loop.
                if compact_src is None:
                    compact_src = np.tile(
                        np.arange(width, dtype=np.int32), (self.slots, 1)
                    )
                    compact_n = np.zeros((self.slots,), np.int32)
                    compact_start = np.zeros((self.slots,), np.int32)
                compact_src[i, 1:a + 1] = kept
                compact_n[i] = a + 1
                compact_start[i] = old_clen
            self._slot_clen[i] = old_clen + 1 + a
        if compact_src is not None:
            # ONE batched gather-to-front for every tree commit of the
            # tick (the device table still maps the rolled-back blocks —
            # unmapping below is host bookkeeping that only reaches the
            # device at the next tick's sync, after this gather ran).
            self.cache = self._compact(
                self.cache, jnp.asarray(compact_start),
                jnp.asarray(compact_src), jnp.asarray(compact_n),
            )
        if self._paged:
            for i in spec_plan:
                if self._slot_state[i] == "live":  # retired slots freed
                    self._spec_unmap(i)
        self._spec_proposed += t_prop
        self._spec_accepted += t_acc
        self._spec_verifies += t_ver
        if t_prop:
            self._spec_ticks += 1
        if obs.REGISTRY.enabled and self._spec_proposed:
            _SPEC_ACCEPT_RATIO.set(
                self._spec_accepted / self._spec_proposed
            )
        self._tick_spec = (t_slots, t_prop, t_acc)
        return emitted_total

    def _consume_chunk(self, slot: int, n: int,
                       last: bool) -> Tuple[np.ndarray, bool]:
        """Host-side bookkeeping of one scheduled chunk — the ONE copy the
        fused and staged paths share: slice the prompt rows, advance the
        slot's running position, and on the final chunk move the slot to
        ``await`` (its first sampled token lands in this tick's batched
        fetch). Returns the token rows and whether this chunk STARTS the
        slot's prefill (pos == the admission's start offset — 0 cold, the
        matched length on a hit; the step resets the slot's length to
        that offset before the write)."""
        pos = self._prefill_pos[slot]
        rows = self._prompt_np[slot][pos:pos + n]
        self._prefill_pos[slot] = pos + n
        self._chunk_k[slot] += 1
        if last:
            self._slot_state[slot] = "await"
            if slot in self._prefill_fifo:  # whole-admission suffix
                self._prefill_fifo.remove(slot)  # chunks never enqueue
        if obs.REGISTRY.enabled:
            _PREFILL_CHUNKS.inc()
        if obs.TRACER.active:
            plen = len(self._slot_req[slot].prompt)
            obs.instant("prefill_chunk", cat="serving", args={
                "rid": self._slot_req[slot].uid, "slot": slot,
                # Nominal k/N (a tick's budget can shrink a chunk, so k
                # may run past N; the pos/plen pair is the exact truth).
                "chunk": f"{self._chunk_k[slot]}/"
                         f"{-(-plen // self.prefill_chunk)}",
                "n": int(n), "pos": pos + n, "prompt_len": plen,
            })
        return rows, pos == self._prefill_start[slot]

    def _run_staged_chunk(self, slot: int, n: int, last: bool) -> None:
        """Quantized chunked admission: advance one slot's staged exact
        prefill by ``n`` tokens; the final chunk quantizes + inserts."""
        plen = len(self._slot_req[slot].prompt)
        rows, first = self._consume_chunk(slot, n, last)
        mat = np.zeros((1, self._chunk_bucket(n)), np.int32)
        mat[0, :n] = rows
        n_vec = jnp.asarray([n], jnp.int32)
        reset = jnp.asarray([first])
        reset_val = jnp.asarray([self._prefill_start[slot]], jnp.int32)
        if last:
            # The quantized insert scatters the whole staged prompt into
            # the slot — its blocks must all be mapped first.
            self._ensure_blocks(slot, plen)
            self._sync_table()
            self._staging, self.cache, self.tok, self._lp, \
                last_row = self._stage_final(
                    self.params, jnp.asarray(mat), n_vec, self._staging,
                    self.cache, self.tok, self._lp, jnp.int32(slot),
                    jnp.int32(plen), reset, reset_val,
                    self._keys[slot], jnp.float32(self._temp_np[slot]),
                    jnp.int32(self._topk_np[slot]),
                    jnp.int32(self._prefill_start[slot]),
                )
            if self._slot_req[slot].uid in self._families:
                self._slot_logits[slot] = last_row[0]
            # The staging cache now holds the prompt's EXACT rows (the
            # quantized copy went into the slot) — publish before the
            # next prompt overwrites them.
            self._publish_prefix(slot)
        else:
            self._staging = self._stage_chunk(
                self.params, jnp.asarray(mat), n_vec, self._staging,
                reset, reset_val,
            )

    def _free_slot_resources(self, slot: int) -> None:
        """Release everything a slot holds — prefix pins, private paged
        blocks, CoW refs, unspent reservation — and mark it free.
        Shared exit arc of ``_retire`` and ``_tree_close``."""
        self._slot_req[slot] = None
        self._slot_tokens[slot] = []
        self._slot_state[slot] = "free"
        self._prompt_np[slot] = None
        self._slot_logits[slot] = None
        if self._prefix is not None and self._slot_nodes[slot]:
            # The request's pinned prefix path becomes evictable.
            self._prefix.release(self._slot_nodes[slot])
            self._slot_nodes[slot] = []
        if self._paged:
            # Blocks the tree adopted stay cached (pins just dropped);
            # the slot's remaining private blocks — decode tail, partial
            # prompt block, unpublished spans — go back to the free list,
            # along with any unspent worst-case reservation (early EOS).
            for bid in self._slot_private[slot]:
                self._pool.free_private(bid)
            self._slot_private[slot] = set()
            # CoW-shared fork ancestors (ISSUE 15): this owner's
            # refcount drops on EVERY exit arc; the last branch's
            # release frees the block.
            for bid in self._slot_shared[slot]:
                self._pool.release_shared(bid)
            self._slot_shared[slot] = set()
            self._live_reset.pop(slot, None)
            if self._slot_reserve[slot]:
                self._pool.unreserve(self._slot_reserve[slot])
                self._slot_reserve[slot] = 0
            self._host_table[slot, :] = 0  # stale ids must never be read
            self._slot_nblocks[slot] = 0
            self._table_dirty = True
            # The pin releases above can grow EVICTABILITY without
            # touching the free list — clear the admit loop's deferral
            # latch so the queue head retries.
            self._pool.gen += 1

    def _tree_retire_all(self, slot: int, fam: _ForkFamily, tick: int,
                         outcome: str,
                         results: List[RequestResult]) -> None:
        """Cancel/deadline/shed a started tree family: every live
        branch finishes with the slot's outcome, then the slot closes."""
        now = time.monotonic()
        for b in range(fam.branches):
            if fam.br_live[b]:
                self._tree_finish_branch(slot, fam, b, outcome, tick,
                                         now, results)
        self._tree_close(slot, fam, tick)

    def _retire(self, slot: int, tick: int, outcome: str,
                results: List[RequestResult]) -> None:
        """Free a slot on ANY outcome arc. The happy paths (eos/budget)
        and the robustness paths (cancelled/deadline) release the exact
        same resources — prefix pins, private paged blocks, unspent
        reservations — so retiring a request mid-prefill or mid-stream
        is just this, earlier (cancellation is cheap by construction:
        PagedAttention's unmap, arXiv:2309.06180)."""
        tfam = self._tree_fams.get(slot)
        if tfam is not None:
            if tfam.forked:
                # Started tree family: per-branch results, shared close.
                self._tree_retire_all(slot, tfam, tick, outcome, results)
                return
            # Unstarted (still prefilling / awaiting first token): the
            # plain retire below settles it — _cancel_unforked's tree
            # arm synthesizes the sibling results.
            self._tree_fams.pop(slot)
        req = self._slot_req[slot]
        admit_tick, visible_at = self._slot_admit[slot]
        now = time.monotonic()
        result = RequestResult(
            uid=req.uid,
            tokens=list(self._slot_tokens[slot]),
            prompt_len=len(req.prompt),
            arrival_tick=req.arrival_tick,
            admit_tick=admit_tick,
            finish_tick=tick,
            queue_wait_s=self._slot_wait[slot],
            completion_s=max(now - visible_at, 0.0),
            outcome=outcome,
            ttft_s=self._slot_ttft[slot],
            prefix_hit_tokens=self._slot_prefix_hit[slot],
            index=self._slot_index[slot],
            cum_logprob=self._slot_cum_lp[slot],
        )
        results.append(result)
        if outcome in (OUTCOME_EOS, OUTCOME_BUDGET):
            self.slo.observe_request(
                self._slot_ttft[slot], self._slot_max_tbt[slot]
            )
        elif outcome in (OUTCOME_DEADLINE, OUTCOME_SHED, OUTCOME_ERROR):
            # The server failed this request; a client cancellation
            # (the remaining arc) is not the server's SLO miss.
            self.slo.observe_miss()
        if slot in self._prefill_fifo:
            # Cancelled/expired mid-prefill: leave the chunk plan (and,
            # under int8, release the one-prompt-at-a-time staging
            # latch — the staged rows are garbage the next admission's
            # first-chunk reset overwrites).
            self._prefill_fifo.remove(slot)
        span = self._slot_span[slot]
        if span is not None:
            if obs.TRACER.active:
                span.set(
                    outcome=outcome, tokens=len(self._slot_tokens[slot]),
                    ttft_s=round(self._slot_ttft[slot], 6),
                )
                obs.instant("request_retired", cat="serving", args={
                    "rid": req.uid, "slot": slot, "tick": tick,
                    "outcome": outcome,
                })
                if req.trace is not None:
                    # Finish point of the cross-process flow — emitted
                    # while the request span is still open so the arrow
                    # binds to it (bp:"e").
                    obs.flow("f", obs.flow_id(req.trace[0]))
            span.__exit__(None, None, None)
            self._slot_span[slot] = None
        if obs.REQLOG.enabled:
            result.ledger = obs.REQLOG.finish(
                req.uid, outcome=outcome, finish_tick=tick,
                tokens_decoded=len(result.tokens), now=now,
            )
        self._free_slot_resources(slot)
        if obs.REGISTRY.enabled:
            _REQUESTS.labels(outcome=outcome).inc()
        # Fork-family join bookkeeping (ISSUE 15): a parent retiring
        # before its first token takes its unforked siblings with it;
        # the per-branch finish is (maybe) delivered, then the family
        # collects the branch — the LAST one joins (best-of-n selects
        # and streams its winner there).
        fam = self._families.get(req.uid)
        if fam is not None and slot == fam.parent_slot and not fam.forked:
            self._cancel_unforked(fam, result, tick, results)
        self._notify_finish(req, result, fam)
        if fam is not None:
            self._family_branch_done(fam, result)
        if not any(rq is not None and rq.uid == req.uid
                   for rq in self._slot_req):
            self._uid_next_index.pop(req.uid, None)

    def serve(self, requests: Union[Sequence[Request], RequestSource],
              max_ticks: Optional[int] = None) -> ServeReport:
        """Run the tick loop until the request source drains.

        ``requests`` is a pre-built trace (the legacy shape — admitted in
        arrival order, FIFO per arrival tick, every request validated up
        front) or a live :class:`RequestSource` (the ingress shape —
        requests appear as clients submit them, invalid ones finish with
        outcome ``error`` instead of raising, and the loop idles on
        :meth:`RequestSource.wait` between arrivals). Each tick starts
        with the control sweep: mailboxed cancellations apply, expired
        deadlines shed their requests, and a requested drain stops
        admission and sheds the queue. ``max_ticks`` bounds runaway loops
        (raises if work remains)."""
        live = isinstance(requests, RequestSource)
        if live:
            source: RequestSource = requests
        else:
            for r in requests:
                self._validate(r)
            source = StaticRequestSource(requests)
            with self._ctl_lock:
                # A previous run's stale mailbox must not cancel or
                # drain this fresh synthetic trace (uids recycle). Live
                # sources deliberately SKIP this reset: a drain or
                # cancel issued between spawning the engine thread and
                # the loop starting must be honored, not wiped.
                self._cancel_uids.clear()
                self._draining = False
        pending: deque = deque()  # visible, validated, unadmitted
        cancel_carry: Dict[int, int] = {}  # unmatched cancels, sweep TTL
        # A live server runs indefinitely: bound its retention (the
        # report then covers the most recent window) — a finite trace
        # keeps everything, as before.
        results: Any = deque(maxlen=4096) if live else []
        visible_wall: Dict[int, float] = {}
        tbt: Any = deque(maxlen=1 << 16) if live else []
        tick = 0
        decode_ticks = 0
        occupancy = 0
        tokens = 0
        prefix0 = self._prefix.stats() if self._prefix is not None else None
        hit_bytes0 = self._hit_bytes_moved
        spec0 = (self._spec_proposed, self._spec_accepted,
                 self._spec_ticks, self._spec_verifies)
        fork0 = (self._forks_life, self._fork_shared_life)
        tree0 = (self._tree_fams_life, self._tree_branches_life)
        if self._paged:
            self._peak_blocks_used = self._pool.used
            self._defer_gen = -1  # stale latch must not defer a fresh run
        host0 = (self._host_pool.stats()
                 if self._host_pool is not None else None)
        t0 = time.monotonic()

        try:
            while True:
                if max_ticks is not None and tick >= max_ticks:
                    raise RuntimeError(
                        f"serve() exceeded max_ticks={max_ticks} with "
                        f"{len(pending)} pending request(s)"
                    )
                now = time.monotonic()
                self._tick_prefix_hits = 0
                self._tick_prefix_reused = 0
                self._tick_restored = 0
                self._tick_spec = (0, 0, 0)
                self._tick_cancelled = 0
                self._tick_deadline = 0
                self._tick_shed = 0
                self._tick_forks = 0
                self._tick_fork_shared = 0
                self._tick_tree_branches = 0
                self._tick_branch_retired = 0

                # Ingest newly visible requests. A live source's invalid
                # request must not kill the loop serving everyone else —
                # it finishes unserved with outcome 'error' (static
                # traces were validated up front and still raise).
                # lint: mirror[ingest] begin
                for r in source.poll(tick):
                    vis = r.visible_at if r.visible_at is not None else now
                    try:
                        self._validate(r)
                    except ValueError as e:
                        log.warning("rejecting request %s: %s", r.uid, e)
                        self._finish_unadmitted(
                            r, tick, OUTCOME_ERROR, results, vis, now
                        )
                        continue
                    pending.append(r)
                    visible_wall[r.uid] = vis
                    if obs.TRACER.active:
                        obs.instant("request_queued", cat="serving",
                                    args={"rid": r.uid, "tick": tick})
                # lint: mirror[ingest] end

                # Control sweep (ISSUE 10): mailboxed cancellations,
                # expired deadlines, drain — applied at tick start so
                # every mutation stays on the loop thread. Order within
                # the sweep: cancellation beats deadline beats drain-shed
                # (a disconnected client's request is 'cancelled' even if
                # its deadline also just expired); EOS/budget from the
                # PREVIOUS tick already retired, so a request finishing
                # and expiring on the same tick keeps its happy outcome.
                cancels, draining = self._take_control()
                cancels |= set(cancel_carry)
                if cancels:
                    # lint: mirror[cancel-queued] begin
                    matched = set()
                    for r in [r for r in pending if r.uid in cancels]:
                        pending.remove(r)
                        matched.add(r.uid)
                        self._tick_cancelled += 1
                        self._finish_unadmitted(
                            r, tick, OUTCOME_CANCELLED, results,
                            visible_wall.pop(r.uid, now), now,
                        )
                    # lint: mirror[cancel-queued] end
                    for i, rq in enumerate(self._slot_req):
                        if rq is not None and rq.uid in cancels:
                            matched.add(rq.uid)
                            self._tick_cancelled += 1
                            self._retire(i, tick, OUTCOME_CANCELLED,
                                         results)
                    # A cancel can race its own request's submission: the
                    # handler's submit may land AFTER this tick's poll
                    # while the cancel lands BEFORE this sweep. Carry
                    # unmatched uids for a couple of sweeps so the
                    # request is caught the moment it is ingested;
                    # genuinely unknown/finished uids age out as no-ops.
                    # lint: mirror[cancel-carry] begin
                    for uid in cancels - matched:
                        if uid not in cancel_carry:
                            cancel_carry[uid] = 2
                        else:
                            cancel_carry[uid] -= 1
                            if cancel_carry[uid] <= 0:
                                del cancel_carry[uid]
                    for uid in matched:
                        cancel_carry.pop(uid, None)
                    # lint: mirror[cancel-carry] end
                # Expired in queue: reject unserved — admitting work
                # that can no longer meet its deadline only steals
                # tick budget from requests that still can.
                # lint: mirror[deadline-queued] begin
                for r in [r for r in pending
                          if r.deadline_s is not None
                          and now >= r.deadline_s]:
                    pending.remove(r)
                    self._tick_deadline += 1
                    self._finish_unadmitted(
                        r, tick, OUTCOME_DEADLINE, results,
                        visible_wall.pop(r.uid, now), now,
                    )
                # lint: mirror[deadline-queued] end
                for i, rq in enumerate(self._slot_req):
                    if (rq is not None and rq.deadline_s is not None
                            and now >= rq.deadline_s):
                        # Expired in flight: retire mid-stream; the
                        # partial tokens already streamed stand.
                        self._tick_deadline += 1
                        self._retire(i, tick, OUTCOME_DEADLINE, results)
                if draining:
                    # Graceful drain: close the source, shed everything
                    # still queued, keep stepping the in-flight slots to
                    # completion.
                    # lint: mirror[drain-shed] begin
                    source.close()
                    while pending:
                        r = pending.popleft()
                        self._tick_shed += 1
                        self._finish_unadmitted(
                            r, tick, OUTCOME_SHED, results,
                            visible_wall.pop(r.uid, now), now,
                        )
                    # lint: mirror[drain-shed] end

                # Copy-on-write fork arc (ISSUE 15): mailboxed
                # fork(uid)s branch live requests onto free slots
                # (deferred ones retry from the carry for a few sweeps).
                # lint: mirror[fork] begin
                forks = self._take_forks()
                if forks or self._fork_carry:
                    self._apply_forks(forks, tick, pending)
                # lint: mirror[fork] end

                # Admit: oldest visible request per free slot. Chunked
                # admission is pure bookkeeping (the chunks run inside the
                # tick); the staged (quantized) variant holds one prompt in
                # flight at a time, so admission waits for the stage.
                free = self._free_slots()
                while free and pending:
                    if self._staged_prefill and self._prefill_fifo:
                        break
                    # An n>1 / best-of-n family admits ATOMICALLY: the
                    # parent's slot plus one fpend slot per sibling
                    # (FIFO — the family waits rather than skip-ahead),
                    # so two half-admitted families can never deadlock
                    # each other's slots.
                    branches = self._branches(pending[0])
                    # A tree-sibling family (ISSUE 20) needs ONE slot
                    # however many branches it decodes.
                    tree_adm = (branches > 1
                                and self._tree_sibling_ok(pending[0]))
                    if (1 if tree_adm else branches) > len(free):
                        break
                    resv = None
                    if self._paged:
                        # Worst-case block reservation (minus what a
                        # prefix hit shares). Failure DEFERS: the
                        # request stays queued — FIFO, no skip-ahead —
                        # until retires/evictions free blocks. This is
                        # what lets --slots exceed the pool's contiguous
                        # equivalent instead of failing on a shape. The
                        # generation latch skips the O(prompt) re-match
                        # + O(tree) evictability recount on ticks where
                        # availability cannot have grown since the last
                        # failed attempt.
                        if self._defer_gen == self._pool.gen:
                            break
                        resv = self._paged_reserve(pending[0])
                        if resv is None:
                            self._defer_gen = self._pool.gen
                            break
                    req = pending.popleft()
                    slot = free.pop(0)
                    vis = visible_wall.pop(req.uid, now)
                    if branches > 1:
                        # The family exists BEFORE the admission runs:
                        # whole-admission prefill stashes the family's
                        # prompt-end logits synchronously inside _admit.
                        if tree_adm:
                            self._admit_tree_family(req, slot)
                        else:
                            self._admit_family(req, slot, free, resv)
                    self._admit(req, slot, tick, vis, resv)
                queue_depth = len(pending)  # visible but still unadmitted

                if not pending and all(st == "free"
                                       for st in self._slot_state):
                    # Nothing to do this tick. Drained (source exhausted
                    # or draining): done. Synthetic trace: fast-forward
                    # to the next arrival instead of spinning empty
                    # decode ticks. Live feeder: report idle (the
                    # /healthz contract — an idle server is not a
                    # stalled one) and block briefly for submissions
                    # (wakes early on submit/close).
                    if FLIGHT.enabled:
                        rec = None
                        # lint: mirror[sweep-only] begin
                        if (self._tick_cancelled or self._tick_deadline
                                or self._tick_shed):
                            # The sweep retired work and left the tick
                            # idle; without this record the counters are
                            # zeroed at the next tick top and the storm
                            # vanishes from the black box.
                            rec = {
                                "tick": tick,
                                "sweep_only": True,
                                "occupancy": 0,
                                "queue_depth": queue_depth,
                                "pending": len(pending),
                                "cancelled": self._tick_cancelled,
                                "deadline_expired": self._tick_deadline,
                                "shed": self._tick_shed,
                                "draining": draining,
                            }
                        # lint: mirror[sweep-only] end
                        if rec is not None:
                            FLIGHT.record(rec)
                    # lint: mirror[idle] begin
                    if source.exhausted or draining:
                        break
                    nxt = source.next_arrival()
                    if nxt is not None:
                        tick = max(tick + 1, nxt)
                    else:
                        if FLIGHT.enabled:
                            FLIGHT.mark_idle()
                        source.wait(0.05)
                    continue
                    # lint: mirror[idle] end

                # Plan this tick's prefill chunks (chunked admission
                # only). While a tree family decodes, chunks clamp to
                # the int32 tree-bitmask width — the sibling bundle
                # must never be forced onto a Tq > 32 program.
                plan = (self._plan_chunks(
                            max_n=32 if self._tree_fams else None)
                        if self.admission == "chunked" else [])
                chunk_tokens = sum(n for _, n, _ in plan)
                # The staged path rebinds ``plan`` to []; keep the tick's
                # real chunk plan reachable for the flight record (a
                # reference, not a copy — free when the recorder is off).
                plan_rec = plan
                live_idx = [i for i, st in enumerate(self._slot_state)
                            if st == "live"]
                if obs.REGISTRY.enabled:
                    _SLOTS_OCCUPIED.set(len(live_idx))
                    _TREE_BRANCHES.set(sum(
                        sum(f.br_live) for f in self._tree_fams.values()
                        if f.forked
                    ))

                # The per-tick mixed-step span: occupancy, chunk-budget
                # spent, and queue depth tagged on the one program the
                # tick dispatches (host_sync set before close).
                tick_span = obs.span(
                    "serving:tick", cat="serving",
                    args=None if not obs.TRACER.active else {
                        "tick": tick, "occupancy": len(live_idx),
                        "prefilling": len(self._prefill_fifo),
                        "chunk_tokens": chunk_tokens,
                        "queue_depth": queue_depth,
                    },
                )
                with tick_span:
                    ran_staged = False
                    if self._staged_prefill and plan:
                        for slot, n, last in plan:
                            self._run_staged_chunk(slot, n, last)
                        plan = []
                        ran_staged = True

                    stepped = False
                    spec_plan: Dict[int, PackedSpec] = {}
                    tree_plan: Dict[
                        int, Tuple[PackedSpec, List[int], int]
                    ] = {}
                    all_tok_dev = None
                    fused_dev = None
                    spec_width = 0
                    if self._tree_fams:
                        # Token-tree sibling decode (ISSUE 20): every
                        # started family's live suffixes pack into one
                        # verify-shaped bundle for its ONE slot. Packing
                        # is pure host work, same as drafting.
                        for i, tfam in self._tree_fams.items():
                            if tfam.forked \
                                    and self._slot_state[i] == "live":
                                tree_plan[i] = self._pack_tree(tfam)
                    if self._speculate and live_idx:
                        # Draft-and-verify (ISSUE 8): every live slot's
                        # tick becomes a verify chunk — tip token at row
                        # 0, up to draft_k candidates behind it (m = 0 is
                        # a plain decode row). Drafting is pure host work.
                        # A tick whose prefill chunks widen Tq past 32
                        # cannot run the int32 tree bitmasks — trees fall
                        # back to their root-path chains for that tick.
                        chunk_tq = (
                            self._chunk_bucket(max(n for _, n, _ in plan))
                            if plan else 1
                        )
                        for i in live_idx:
                            spec_plan[i] = self._draft_slot(
                                i, tree_ok=chunk_tq <= 32
                            )
                    if (self._speculate and (plan or spec_plan)) \
                            or tree_plan:
                        # THE verify tick: decode-verify rows (draft
                        # windows under speculation, sibling bundles
                        # under tree decode) and prefill chunks share
                        # one compiled program, exactly like the mixed
                        # tick — per-row draws ride back as a fused
                        # output for the accept walk / branch tips.
                        rows_all = [p.rows for p in spec_plan.values()]
                        rows_all += [pk.rows
                                     for pk, _, _ in tree_plan.values()]
                        rows_max = max(rows_all or [1])
                        # Draft-less ticks (nothing proposed anywhere)
                        # run the Tq=1 shape — low-acceptance traffic
                        # must not pay the padded verify bucket for
                        # nothing.
                        tq = (
                            self._spec_bucket(rows_max) if rows_max > 1
                            else 1
                        )
                        if plan:
                            tq = max(tq, self._chunk_bucket(
                                max(n for _, n, _ in plan)
                            ))
                        spec_width = tq
                        mat = np.zeros((self.slots, tq), np.int32)
                        n_vec = np.zeros((self.slots,), np.int32)
                        reset = np.zeros((self.slots,), bool)
                        reset_val = np.zeros((self.slots,), np.int32)
                        emit = np.zeros((self.slots,), bool)
                        # Parked first tokens (whole-admission awaits)
                        # exist only in the device token vector — their
                        # row 0 must come from there, everyone else's
                        # from the host matrix. Computed BEFORE chunk
                        # consumption flips final-chunk slots to await.
                        use_dev0 = np.asarray(
                            [st == "await" for st in self._slot_state]
                        )
                        sidx = np.asarray(
                            [len(t) for t in self._slot_tokens], np.int32
                        )
                        # Per-ROW key-chain operands (ISSUE 20): the
                        # defaults put every row on the slot's own spec
                        # chain — branch < 0 folds fold_in(slot_key,
                        # stream index); sibling rows overwrite both
                        # with the fork-slot chain's (branch, index).
                        branch_m = np.full((self.slots, tq), -1,
                                           np.int32)
                        ridx_m = sidx[:, None] + np.tile(
                            np.arange(tq, dtype=np.int32),
                            (self.slots, 1),
                        )
                        need_tree = bool(tree_plan)
                        for i, pack in spec_plan.items():
                            r = pack.rows
                            self._ensure_blocks(i, self._slot_clen[i] + r)
                            mat[i, :r] = pack.row_tokens
                            n_vec[i] = r
                            # reset_val IS the rollback: the device
                            # length over-counts by last tick's rejected
                            # rows until this reset.
                            reset[i] = True
                            reset_val[i] = self._slot_clen[i]
                            ridx_m[i, :r] = sidx[i] + pack.depth
                            if not np.array_equal(
                                pack.depth, np.arange(r, dtype=np.int32)
                            ):
                                need_tree = True
                        for i, (pack, order, s) in tree_plan.items():
                            tfam = self._tree_fams[i]
                            r = pack.rows
                            self._ensure_blocks(i, tfam.base_len + r)
                            mat[i, :r] = pack.row_tokens
                            n_vec[i] = r
                            # The replay reset: committed rows freeze at
                            # the shared ancestors; every suffix row is
                            # re-derived into the window PAST them.
                            reset[i] = True
                            reset_val[i] = tfam.base_len
                            branch_m[i, :r] = np.repeat(np.asarray(
                                [tfam.br_index[b] for b in order],
                                np.int32,
                            ), s)
                            ridx_m[i, :r] = tfam.fork_len + pack.depth
                        if not self._speculate:
                            # Plain live slots ride the tree tick as
                            # n=1 decode rows (the mixed-step contract),
                            # including a forked child's one pending
                            # length reset.
                            for i in live_idx:
                                if i in tree_plan:
                                    continue
                                self._ensure_blocks(
                                    i, len(self._slot_req[i].prompt)
                                    + len(self._slot_tokens[i])
                                )
                                mat[i, 0] = self._tok_host[i]
                                n_vec[i] = 1
                                emit[i] = True
                            for i in list(self._live_reset):
                                if self._slot_state[i] == "live" \
                                        and i not in tree_plan:
                                    reset[i] = True
                                    reset_val[i] = \
                                        self._live_reset.pop(i)
                        for slot, n, last in plan:
                            self._ensure_blocks(
                                slot, self._prefill_pos[slot] + n
                            )
                            rows, first = self._consume_chunk(slot, n,
                                                              last)
                            mat[slot, :n] = rows
                            n_vec[slot] = n
                            reset[slot] = first
                            reset_val[slot] = self._prefill_start[slot]
                            emit[slot] = last
                        self._sync_table()
                        args = (
                            self.params, jnp.asarray(mat), self.tok,
                            jnp.asarray(use_dev0), jnp.asarray(n_vec),
                            jnp.asarray(reset), jnp.asarray(reset_val),
                            jnp.asarray(emit),
                        )
                        extra = (
                            self._keys, jnp.asarray(self._temp_np),
                            jnp.asarray(self._topk_np),
                            jnp.asarray(sidx), self._lp,
                            jnp.asarray(self._salt_np),
                            jnp.asarray(branch_m), jnp.asarray(ridx_m),
                        )
                        if need_tree:
                            # Per-slot depths + ancestor bitmasks; chain
                            # slots (and prefill chunks) ride the arange/
                            # lower-triangular defaults — the causal rule
                            # bit-for-bit.
                            depth_m = np.tile(
                                np.arange(tq, dtype=np.int32),
                                (self.slots, 1),
                            )
                            bits_m = np.broadcast_to(
                                np.tril(np.ones((tq, tq), bool)),
                                (self.slots, tq, tq),
                            ).copy()
                            for i, pack in spec_plan.items():
                                r = pack.rows
                                depth_m[i, :r] = pack.depth
                                bits_m[i, :r, :r] = pack.anc
                            for i, (pack, _, _) in tree_plan.items():
                                r = pack.rows
                                depth_m[i, :r] = pack.depth
                                bits_m[i, :r, :r] = pack.anc
                            self.tok, self._lp, all_tok_dev, last_dev, \
                                self.cache = self._spec_tree(
                                    *args, jnp.asarray(depth_m),
                                    jnp.asarray(bits_m), self.cache,
                                    *extra,
                                )
                        else:
                            self.tok, self._lp, all_tok_dev, last_dev, \
                                self.cache = self._spec_lin(
                                    *args, self.cache, *extra,
                                )
                        stepped = True
                        for slot, n, last in plan:
                            # Stash prompt-end logits for slots whose
                            # fork/tree family expands at this tick's
                            # awaits pass (ISSUE 15/20).
                            if last and self._slot_req[slot].uid \
                                    in self._families:
                                self._slot_logits[slot] = last_dev[slot]
                        if self._prefix is not None:
                            for slot, n, last in plan:
                                if last:
                                    self._publish_prefix(slot)
                    elif plan:
                        # The fused mixed tick: decode rows + prefill
                        # chunks in ONE compiled program; chunks write
                        # straight into each slot's region of the batch
                        # cache at its running offset.
                        tq = self._chunk_bucket(max(n for _, n, _ in plan))
                        mat = np.zeros((self.slots, tq), np.int32)
                        n_vec = np.zeros((self.slots,), np.int32)
                        reset = np.zeros((self.slots,), bool)
                        reset_val = np.zeros((self.slots,), np.int32)
                        emit = np.zeros((self.slots,), bool)
                        for i in live_idx:
                            self._ensure_blocks(
                                i, len(self._slot_req[i].prompt)
                                + len(self._slot_tokens[i])
                            )
                            mat[i, 0] = self._tok_host[i]
                            n_vec[i] = 1
                            emit[i] = True
                        # Freshly forked children (ISSUE 15): their one
                        # device-length reset to the fork point.
                        for i in list(self._live_reset):
                            # Applied only once the slot is LIVE — an
                            # awaiting sibling keeps its pending reset
                            # until its first consuming tick.
                            if self._slot_state[i] == "live":
                                reset[i] = True
                                reset_val[i] = self._live_reset.pop(i)
                        for slot, n, last in plan:
                            self._ensure_blocks(
                                slot, self._prefill_pos[slot] + n
                            )
                            rows, first = self._consume_chunk(slot, n, last)
                            mat[slot, :n] = rows
                            n_vec[slot] = n
                            reset[slot] = first
                            reset_val[slot] = self._prefill_start[slot]
                            emit[slot] = last
                        sidx = np.asarray(
                            [len(t) for t in self._slot_tokens], np.int32
                        )
                        self._sync_table()
                        self.tok, self._lp, fused_dev, last_dev, \
                            self.cache = self._mixed(
                                self.params, jnp.asarray(mat),
                                jnp.asarray(n_vec), jnp.asarray(reset),
                                jnp.asarray(reset_val),
                                jnp.asarray(emit), self.cache,
                                self._keys, jnp.asarray(self._temp_np),
                                jnp.asarray(self._topk_np),
                                jnp.asarray(sidx), self._lp,
                            )
                        stepped = True
                        for slot, n, last in plan:
                            # Stash prompt-end logits for slots whose
                            # fork family expands at this tick's awaits
                            # pass (ISSUE 15).
                            if last and self._slot_req[slot].uid \
                                    in self._families:
                                self._slot_logits[slot] = last_dev[slot]
                        if self._prefix is not None:
                            # Final chunks just completed their prompts in
                            # the batch cache — publish the new blocks
                            # while this admission's rows are fresh.
                            for slot, n, last in plan:
                                if last:
                                    self._publish_prefix(slot)
                    elif live_idx:
                        # Pure-decode tick: the SAME program at the Tq=1
                        # bucket, tokens carried on device (awaiting slots
                        # hold their parked first token through n=0 /
                        # emit=False).
                        n_vec = np.zeros((self.slots,), np.int32)
                        emit = np.zeros((self.slots,), bool)
                        reset = np.zeros((self.slots,), bool)
                        reset_val = np.zeros((self.slots,), np.int32)
                        n_vec[live_idx] = 1
                        emit[live_idx] = True
                        for i in list(self._live_reset):
                            # A forked child's device length learns the
                            # fork point at its first consuming tick
                            # (await siblings keep theirs pending).
                            if self._slot_state[i] == "live":
                                reset[i] = True
                                reset_val[i] = self._live_reset.pop(i)
                        for i in live_idx:
                            self._ensure_blocks(
                                i, len(self._slot_req[i].prompt)
                                + len(self._slot_tokens[i])
                            )
                        sidx = np.asarray(
                            [len(t) for t in self._slot_tokens], np.int32
                        )
                        self._sync_table()
                        self.tok, self._lp, fused_dev, _, \
                            self.cache = self._mixed(
                                self.params, self.tok[:, None],
                                jnp.asarray(n_vec),
                                jnp.asarray(reset),
                                jnp.asarray(reset_val),
                                jnp.asarray(emit), self.cache,
                                self._keys, jnp.asarray(self._temp_np),
                                jnp.asarray(self._topk_np),
                                jnp.asarray(sidx), self._lp,
                            )
                        stepped = True

                    awaits = [i for i, st in enumerate(self._slot_state)
                              if st == "await"]
                    host_sync = bool(awaits or live_idx)
                    tokens_this_tick = 0
                    alltok_host = None
                    alllp_host = None
                    if host_sync:
                        # THE per-tick host sync: every new token of this
                        # tick — decode samples, fused final-chunk first
                        # tokens, legacy insert first tokens — in one
                        # batched fetch. Only ticks that produced a token
                        # pay it: a fused tick of nothing but mid-prompt
                        # chunks skips the fetch (like the staged path
                        # below), letting consecutive chunks pipeline in
                        # the dispatch queue. A live slot always enters
                        # its tick with a fresh ``_tok_host`` — it went
                        # live inside this block. A verify tick fetches
                        # its fused (S, 1+Tq) output instead: the token
                        # vector AND every row argmax in the same sync.
                        lp_valid = False
                        if all_tok_dev is not None:
                            # lint: allow[host-sync] THE one per-tick fetch (verify ticks: token/logprob vectors + every row draw, one fused array)
                            fused_host = np.asarray(all_tok_dev)
                            self._tok_host = fused_host[:, 0, 0]
                            self._lp_host = np.ascontiguousarray(
                                fused_host[:, 0, 1]
                            ).view(np.float32)
                            alltok_host = fused_host[:, 1:, 0]
                            alllp_host = np.ascontiguousarray(
                                fused_host[:, 1:, 1]
                            ).view(np.float32)
                            lp_valid = True
                        elif fused_dev is not None:
                            # lint: allow[host-sync] THE one per-tick fetch (token vector + bitcast logprobs, one fused array)
                            fh = np.asarray(fused_dev)
                            self._tok_host = fh[:, 0]
                            self._lp_host = np.ascontiguousarray(
                                fh[:, 1]
                            ).view(np.float32)
                            lp_valid = True
                        else:
                            # Awaits-only tick (a synchronous whole
                            # admission parked tokens, nothing stepped):
                            # fetch the carried vectors directly.
                            # lint: allow[host-sync] THE one per-tick fetch (the batched token vector)
                            self._tok_host = np.asarray(self.tok)
                            # lint: allow[host-sync] rides the same sync point (the parked first-token logprobs)
                            self._lp_host = np.asarray(self._lp)
                            lp_valid = True
                        now2 = time.monotonic()
                        if live_idx:
                            decode_ticks += 1
                            occupancy += len(live_idx)
                        for i in awaits:
                            req = self._slot_req[i]
                            first = int(self._tok_host[i])
                            self._slot_tokens[i] = [first]
                            if lp_valid:
                                self._slot_cum_lp[i] = float(
                                    self._lp_host[i]
                                )
                            self._push_token(req, first,
                                             self._slot_index[i])
                            self._slot_state[i] = "live"
                            # Committed cache rows = the prompt; the
                            # first token is the pending tip (spec mode's
                            # rollback ledger starts here).
                            self._slot_clen[i] = len(req.prompt)
                            if self._speculate:
                                hl = self._hist_len[i]
                                self._hist_buf[i, hl] = first
                                self._hist_len[i] = hl + 1
                            _, vis = self._slot_admit[i]
                            self._slot_ttft[i] = max(now2 - vis, 0.0)
                            self._last_tok_t[i] = now2
                            tokens += 1  # the prefill-sampled first token
                            tokens_this_tick += 1
                            self.slo.observe_ttft(self._slot_ttft[i])
                            if obs.REGISTRY.enabled:
                                _TOKENS.inc()  # the prefill's first token
                                _TTFT.observe(self._slot_ttft[i])
                            if obs.TRACER.active:
                                obs.instant(
                                    "first_token", cat="serving", args={
                                        "rid": req.uid, "slot": i,
                                        "tick": tick,
                                        "ttft_s": round(
                                            self._slot_ttft[i], 6),
                                    })
                            if obs.REQLOG.enabled:
                                obs.REQLOG.first_token(req.uid, now=now2)
                            # Family forks happen HERE — before the
                            # parent's EOS/budget check, so even a
                            # one-token parent yields n independent
                            # samples (each sibling re-consumes the
                            # last prompt token and draws its own
                            # first token under its own key).
                            fam = self._families.get(req.uid)
                            if fam is not None and not fam.forked \
                                    and i == fam.parent_slot:
                                if fam.tree:
                                    # Tree-sibling start (ISSUE 20):
                                    # every branch's first token —
                                    # branch 0's EOS/budget included —
                                    # is handled inside, so the generic
                                    # checks below must not run.
                                    n_new = self._tree_family_start(
                                        fam, i, first, tick, now2,
                                        results,
                                    )
                                    tokens += n_new
                                    tokens_this_tick += n_new
                                    continue
                                n_new = self._fork_family(
                                    fam, i, tick, now2, results
                                )
                                tokens += n_new
                                tokens_this_tick += n_new
                            if req.eos_id is not None \
                                    and first == req.eos_id:
                                self._retire(i, tick, OUTCOME_EOS, results)
                            elif req.max_new_tokens <= 1:
                                self._retire(i, tick, OUTCOME_BUDGET,
                                             results)
                        if self._speculate:
                            # Spec mode: live-slot tokens come from the
                            # verify walk over the fetched row draws,
                            # 1..draft_k+1 of them per slot per tick.
                            if spec_plan:
                                n_new = self._spec_commit_all(
                                    spec_plan, alltok_host, alllp_host,
                                    spec_width, now2, tick, results, tbt,
                                )
                                tokens += n_new
                                tokens_this_tick += n_new
                        else:
                            if tree_plan:
                                # Tree mode: each live branch's token is
                                # its last packed row's draw; retires
                                # shrink the family the same tick.
                                n_new = self._tree_commit_all(
                                    tree_plan, alltok_host, alllp_host,
                                    now2, tick, results, tbt,
                                )
                                tokens += n_new
                                tokens_this_tick += n_new
                            for i in live_idx:
                                if i in tree_plan:
                                    continue
                                req = self._slot_req[i]
                                tok_i = int(self._tok_host[i])
                                # Every live slot enters this loop with
                                # a first token already emitted (awaits
                                # pass, or _fork_family for siblings) —
                                # this is always an inter-token gap.
                                self._slot_tokens[i].append(tok_i)
                                if lp_valid:
                                    self._slot_cum_lp[i] += float(
                                        self._lp_host[i]
                                    )
                                self._push_token(req, tok_i,
                                                 self._slot_index[i])
                                tokens += 1
                                tokens_this_tick += 1
                                gap = max(now2 - self._last_tok_t[i], 0.0)
                                tbt.append(gap)
                                self._last_tok_t[i] = now2
                                if gap > self._slot_max_tbt[i]:
                                    self._slot_max_tbt[i] = gap
                                self.slo.observe_tbt(gap)
                                if obs.REGISTRY.enabled:
                                    _TOKENS.inc()
                                    _TBT.observe(gap)
                                if (req.fork_at is not None
                                        and self._slot_index[i] == 0
                                        and len(self._slot_tokens[i])
                                        == req.fork_at):
                                    # Replayable mid-generation branch
                                    # (trace knob): the request forks
                                    # itself through the same mailbox
                                    # an API caller would use.
                                    self.fork(req.uid)
                                if req.eos_id is not None \
                                        and tok_i == req.eos_id:
                                    self._retire(i, tick, OUTCOME_EOS,
                                                 results)
                                elif (len(self._slot_tokens[i])
                                        >= req.max_new_tokens):
                                    self._retire(i, tick, OUTCOME_BUDGET,
                                                 results)
                    if obs.TRACER.active:
                        tick_span.set(host_sync=host_sync,
                                      tokens=tokens_this_tick)

                if self._paged:
                    if self._pool.used > self._peak_blocks_used:
                        self._peak_blocks_used = self._pool.used
                    self._pool.publish_gauges()  # registry-guarded inside
                if self._host_pool is not None:
                    # The staged D2H flush point: demotions this tick's
                    # evictions enqueued complete as ONE batched gather,
                    # after the tick's dispatches (the fetch overlaps
                    # where the loop would otherwise idle toward the
                    # next tick's host work).
                    self._flush_demotions()
                    self._host_pool.publish_gauge()  # registry-guarded

                # The flight recorder's per-tick record (the black box a
                # post-mortem replays); record dict built only when armed.
                if FLIGHT.enabled:
                    rec = {
                        "tick": tick,
                        "t_s": round(now - t0, 6),
                        "occupancy": len(live_idx),
                        "states": list(self._slot_state),
                        "lengths": [self._prefill_pos[i]
                                    if self._slot_state[i] == "prefill"
                                    else len(self._slot_tokens[i])
                                    for i in range(self.slots)],
                        "chunk_plan": [[s, int(n), bool(last)]
                                       for s, n, last in plan_rec],
                        "chunk_tokens": chunk_tokens,
                        "tokens_emitted": tokens_this_tick,
                        "host_sync": host_sync,
                        "queue_depth": queue_depth,
                        "pending": len(pending),
                        "prefix_hits": self._tick_prefix_hits,
                        "prefix_reused": self._tick_prefix_reused,
                        # Robustness arcs this tick (ISSUE 10): the
                        # black box must show a storm the way it showed
                        # a wedge.
                        "cancelled": self._tick_cancelled,
                        "deadline_expired": self._tick_deadline,
                        "shed": self._tick_shed,
                        # Copy-on-write forks this tick (ISSUE 15) and
                        # the ancestor blocks they shared instead of
                        # copying.
                        "forks": self._tick_forks,
                        "shared_blocks": self._tick_fork_shared,
                        # Token-tree sibling decode this tick (ISSUE
                        # 20): branches advanced in-slot, branches
                        # retired out of their bundles.
                        "tree_branches": self._tick_tree_branches,
                        "branch_retired": self._tick_branch_retired,
                        "draining": draining,
                    }
                    if self._paged:
                        # Block occupancy + internal fragmentation (the
                        # fraction of mapped block capacity no written
                        # token occupies) — the paged black-box truths.
                        mapped = sum(self._slot_nblocks)
                        written = 0
                        for i in range(self.slots):
                            st = self._slot_state[i]
                            if st == "prefill":
                                written += self._prefill_pos[i]
                            elif st in ("await", "live"):
                                written += (
                                    len(self._slot_req[i].prompt)
                                    + max(len(self._slot_tokens[i]) - 1, 0)
                                )
                        rec["kv_blocks_used"] = self._pool.used
                        rec["kv_blocks_free"] = self._pool.free_count
                        rec["kv_frag"] = round(
                            1.0 - written / (mapped * self.kv_block), 4
                        ) if mapped else 0.0
                        if self._host_pool is not None:
                            rec["host_blocks_used"] = self._host_pool.used
                            rec["restored_blocks"] = self._tick_restored
                    if self._speculate:
                        s_slots, s_prop, s_acc = self._tick_spec
                        rec["spec_verify"] = {
                            "slots": s_slots,
                            "proposed": s_prop,
                            "accepted": s_acc,
                        }
                    FLIGHT.record(rec)
                self.slo.maybe_export(now)

                # Every executed tick advances the clock by exactly one;
                # idle iterations (fast-forward, live-feeder waits, the
                # drained exit) were handled before the body, so span
                # and flight-record counts track executed ticks.
                tick += 1
        except BaseException as e:
            # The black-box contract: a wedged/crashed tick loop leaves
            # its last ticks on disk before the exception propagates.
            FLIGHT.dump_if_armed(f"engine_error:{type(e).__name__}")
            if obs.TRACER.active:
                obs.instant("engine_error", cat="serving", args={
                    "error": type(e).__name__, "tick": tick,
                })
            raise

        if self._host_pool is not None:
            # A drained run leaves no demotion staged: the ledger's
            # _DEMOTED blocks would otherwise read as leaked capacity.
            self._flush_demotions()
            self._host_pool.publish_gauge()
        if FLIGHT.enabled:
            # Drained, not wedged: /healthz stays 200 "idle" between runs
            # however long this run's last tick ages.
            FLIGHT.mark_idle()
        if obs.REGISTRY.enabled:
            # The branch gauge is set at tick TOP, so a drained run would
            # otherwise freeze it at the last mid-run value; every family
            # closed, so the truth between runs is zero.
            _TREE_BRANCHES.set(0)
        with self._ctl_lock:
            # This run consumed its control state; the engine is reusable
            # (a drain that completed must not auto-drain the next run).
            # Entry only resets for STATIC traces, so a drain/cancel
            # issued between spawning a live engine thread and the loop
            # starting is honored, not wiped.
            self._cancel_uids.clear()
            self._draining = False
        wall = time.monotonic() - t0
        # Final SLO publication: the gauges reflect the run's end state and
        # the report carries the windowed snapshot (goodput + percentiles).
        self.slo.export_gauges()
        slo_snap = self.slo.snapshot()
        prefix_snap: Dict[str, Any] = {}
        if self._prefix is not None:
            p1 = self._prefix.stats()
            reused = p1["tokens_reused"] - prefix0["tokens_reused"]
            prompt_tokens = sum(r.prompt_len for r in results)
            prefix_snap = {
                "hits": p1["hits"] - prefix0["hits"],
                "misses": p1["misses"] - prefix0["misses"],
                "tokens_reused": reused,
                "reused_ratio": round(reused / prompt_tokens, 4)
                if prompt_tokens else 0.0,
                "evictions": p1["evictions"] - prefix0["evictions"],
                "pool_blocks_used": p1["pool_blocks_used"],
                "pool_blocks": p1["pool_blocks"],
                # Device KV bytes the run's hits copied pool->slot: the
                # gather cost under the contiguous layout, identically 0
                # under paged exact serving (reference-in-place).
                "hit_bytes_moved": self._hit_bytes_moved - hit_bytes0,
            }
        kv_snap: Dict[str, Any] = {}
        if self._paged:
            kv_snap = {
                "layout": "paged",
                "block": self.kv_block,
                "pool_blocks": self.kv_blocks,
                "blocks_used": self._pool.used,
                "blocks_free": self._pool.free_count,
                "peak_blocks_used": self._peak_blocks_used,
            }
            if self._forks_life - fork0[0]:
                # Copy-on-write fork accounting for THIS run (ISSUE 15).
                kv_snap["forks"] = self._forks_life - fork0[0]
                kv_snap["fork_blocks_shared"] = (
                    self._fork_shared_life - fork0[1]
                )
            if self._tree_fams_life - tree0[0]:
                # Token-tree sibling accounting for THIS run (ISSUE 20).
                kv_snap["tree_families"] = (
                    self._tree_fams_life - tree0[0]
                )
                kv_snap["tree_branch_ticks"] = (
                    self._tree_branches_life - tree0[1]
                )
            if self._host_pool is not None:
                h1 = self._host_pool.stats()
                kv_snap.update({
                    "host_blocks": h1["host_blocks"],
                    "host_blocks_used": h1["host_blocks_used"],
                    "demotions": h1["demotions"] - host0["demotions"],
                    "restores": h1["restores"] - host0["restores"],
                    "host_drops": h1["host_drops"] - host0["host_drops"],
                })
        spec_snap: Dict[str, Any] = {}
        if self._speculate:
            prop = self._spec_proposed - spec0[0]
            acc = self._spec_accepted - spec0[1]
            spec_snap = {
                "drafter": type(self._drafter).__name__,
                "draft_k": self.draft_k,
                "proposed": prop,
                "accepted": acc,
                "acceptance_rate": round(acc / prop, 4) if prop else 0.0,
                "verify_ticks": self._spec_ticks - spec0[2],
                # Accepted drafts per per-SLOT verify event, plus the
                # always-free bonus token (1 = no win, draft_k + 1 =
                # perfect): the per-slot speedup lever.
                "tokens_per_verify": round(
                    1.0 + acc / (self._spec_verifies - spec0[3]), 4
                ) if self._spec_verifies - spec0[3] else 0.0,
            }
        log.info(
            "served %d request(s): %d tokens over %d decode tick(s), "
            "%.1f tok/s, mean occupancy %.2f/%d",
            len(results), tokens, decode_ticks,
            tokens / wall if wall > 0 else 0.0,
            occupancy / max(decode_ticks, 1), self.slots,
        )
        return ServeReport(
            results=sorted(results, key=lambda r: r.uid),
            ticks=tick,
            wall_s=wall,
            tokens_generated=tokens,
            mean_occupancy=occupancy / max(decode_ticks, 1),
            tbt_s=list(tbt),
            slo=slo_snap,
            prefix=prefix_snap,
            kv=kv_snap,
            spec=spec_snap,
            requests=obs.aggregate_ledgers(
                [r.ledger for r in results if r.ledger is not None]
            ) or {},
        )
