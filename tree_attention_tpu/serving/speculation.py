"""Speculative decoding: drafters, tree packing, and the greedy accept rule.

The mixed-Tq ragged tick (``serving/engine.py``) already runs per-slot
``n_tokens > 1`` through ONE compiled program — that is exactly the
**verify** step of speculative decoding (Leviathan et al.,
arXiv:2211.17192): a cheap drafter proposes candidate continuations, the
big model scores them all in one forward pass, and the longest prefix the
model agrees with commits at once. Under greedy (temperature-0) decoding
the accept rule is exact — row ``j`` of the verify logits is the model's
next token after consuming the path ending at row ``j``, so the committed
stream is *token-for-token identical* to non-speculative decode, only
cheaper per token.

Generalizing the draft from a chain to a token **tree** (SpecInfer,
arXiv:2305.09781) lets one verify pass score several candidate branches
at once under an ancestor-visibility attention mask — the namesake use of
this repo's tree-attention machinery (``forward_step``'s ``tree_mask``).

This module is the host side of that subsystem:

- :class:`DraftProposal` — a packed draft (chain or tree) in topological
  order: ``tokens[i]`` hangs off ``parents[i]`` (``-1`` = the committed
  tip), ``parents[i] < i``.
- :func:`pack_proposal` — the device-facing packing: the verify chunk's
  row tokens (the committed tip at row 0, then the draft nodes), per-row
  depths (RoPE positions) and the ``(rows, rows)`` ancestor mask.
- :func:`accept_longest_path` — the greedy accept walk over the fetched
  per-row argmax tokens: follow matching children from the tip, commit
  the accepted path plus the model's one **bonus** token at the first
  divergence. ``m`` drafted nodes commit between 1 and ``m + 1`` tokens.
- Drafters: :class:`PromptLookupDrafter` (prompt-lookup n-gram — zero
  extra model, the host scans the slot's own emitted history),
  :class:`PromptLookupTreeDrafter` (its multi-branch tree variant), and
  :class:`DraftModelDrafter` (a small draft model served through
  ``models/transformer.py`` behind the same interface).

Everything here is pure host work on small numpy arrays — the device
only ever sees the packed chunk the engine builds from it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class DraftProposal:
    """A packed draft tree: ``tokens[i]`` is a candidate token whose
    parent is draft node ``parents[i]`` (or the committed tip when
    ``parents[i] == -1``). Topological packing (``parents[i] < i``) is
    required — it makes every prefix of the arrays a valid tree, so the
    engine can clamp a proposal to its token budget by truncation."""

    tokens: np.ndarray   # (m,) int32 candidate tokens
    parents: np.ndarray  # (m,) int32, parents[i] < i, -1 = the tip

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, np.int32)
        self.parents = np.asarray(self.parents, np.int32)
        if self.tokens.shape != self.parents.shape or self.tokens.ndim != 1:
            raise ValueError(
                f"tokens/parents must be equal-length vectors, got "
                f"{self.tokens.shape}/{self.parents.shape}"
            )
        if any(p < -1 or p >= i for i, p in enumerate(self.parents)):
            raise ValueError(
                f"parents must be topological (-1 <= parents[i] < i), "
                f"got {self.parents.tolist()}"
            )

    def __len__(self) -> int:
        return len(self.tokens)

    @property
    def is_chain(self) -> bool:
        """A linear draft: node i hangs off node i-1 — verifiable under
        the plain causal mask (no tree-mask program needed)."""
        return all(int(p) == i - 1 for i, p in enumerate(self.parents))

    def truncated(self, m: int) -> "DraftProposal":
        """The first ``m`` nodes (a valid tree by topological packing)."""
        if m >= len(self):
            return self
        return DraftProposal(tokens=self.tokens[:m],
                             parents=self.parents[:m])

    def chain_prefix(self) -> "DraftProposal":
        """The root path through first children — the fallback when the
        verify path cannot run a tree mask (seq-sharded contiguous
        cache): keep following each node's first packed child."""
        keep: List[int] = []
        cur = -1
        while True:
            nxt = next((i for i, p in enumerate(self.parents)
                        if int(p) == cur), None)
            if nxt is None:
                break
            keep.append(nxt)
            cur = nxt
        return DraftProposal(
            tokens=self.tokens[keep],
            parents=np.arange(-1, len(keep) - 1, dtype=np.int32),
        )


@dataclasses.dataclass
class PackedSpec:
    """One slot's verify chunk, device-facing: row 0 is the committed tip
    token (its KV is the one pending write), rows ``1..m`` the draft
    nodes. ``depth[j]`` is the row's distance below the committed length
    (its RoPE offset); ``anc[j]`` its window visibility row (ancestors +
    itself). ``row_parents`` is in ROW ids (tip = row 0)."""

    row_tokens: np.ndarray   # (rows,) int32
    row_parents: np.ndarray  # (rows,) int32; row_parents[0] = -1
    depth: np.ndarray        # (rows,) int32; depth[0] = 0
    anc: np.ndarray          # (rows, rows) bool

    @property
    def rows(self) -> int:
        return len(self.row_tokens)


def pack_proposal(tip_token: int, prop: DraftProposal) -> PackedSpec:
    """Prefix the committed tip as row 0 and derive depths + the ancestor
    mask. A chain proposal yields ``depth == arange`` and a
    lower-triangular ``anc`` — exactly the plain causal contract, so the
    linear program needs neither operand."""
    m = len(prop)
    rows = m + 1
    row_tokens = np.empty((rows,), np.int32)
    row_tokens[0] = tip_token
    row_tokens[1:] = prop.tokens
    row_parents = np.empty((rows,), np.int32)
    row_parents[0] = -1
    row_parents[1:] = prop.parents + 1  # -1 (tip) maps to row 0
    depth = np.zeros((rows,), np.int32)
    anc = np.zeros((rows, rows), bool)
    anc[0, 0] = True
    for j in range(1, rows):
        p = row_parents[j]
        depth[j] = depth[p] + 1
        anc[j] = anc[p]
        anc[j, j] = True
    return PackedSpec(row_tokens=row_tokens, row_parents=row_parents,
                      depth=depth, anc=anc)


def accept_longest_path(
    pack: PackedSpec, row_argmax: Sequence[int]
) -> Tuple[List[int], List[int]]:
    """The greedy accept rule over one slot's fetched verify argmaxes.

    ``row_argmax[j]`` is the model's greedy next token after consuming
    the root path ending at row ``j``. Walk from the tip: at each row,
    the model's true next token either matches a child (accept it, keep
    walking) or nobody (that token is the **bonus** — the model said it,
    so it commits for free). Returns ``(kept_rows, committed_tokens)``:
    ``kept_rows`` the accepted draft rows in path order (ascending, by
    topological packing; row 0 is implicit — its KV is always kept) and
    ``committed_tokens`` the ``len(kept_rows) + 1`` tokens that commit,
    IDENTICAL to what non-speculative greedy decode would have emitted.
    """
    kept: List[int] = []
    committed: List[int] = []
    cur = 0
    rows = pack.rows
    while True:
        nxt = int(row_argmax[cur])
        committed.append(nxt)
        child = next(
            (j for j in range(cur + 1, rows)
             if int(pack.row_parents[j]) == cur
             and int(pack.row_tokens[j]) == nxt),
            None,
        )
        if child is None:
            return kept, committed
        kept.append(child)
        cur = child


def accept_stochastic_path(
    pack: PackedSpec, row_sample: Sequence[int]
) -> Tuple[List[int], List[int]]:
    """The STOCHASTIC accept rule (ISSUE 20) — Leviathan et al.'s
    speculative-sampling ratio test (arXiv:2211.17192) specialized to
    point-mass drafters, which is what every drafter here proposes.

    ``row_sample[j]`` is a draw from the TARGET model's (temperature /
    top-k adjusted) distribution after consuming the path ending at row
    ``j`` (``models.decode.sample_rows`` under the request's
    ``fold_in(key, stream_index)`` chain). For a draft that proposes
    token ``x`` with probability 1, the ratio test accepts with
    probability ``p(x)`` and otherwise emits a sample from the residual
    ``(p - q)+ / Z`` — which for a point mass at ``x`` is exactly
    ``p`` restricted to ``y != x``. Drawing ``s ~ p`` once and
    accepting iff ``s == x`` (else emitting ``s``) realizes both cases
    with the correct joint law, so each committed token is distributed
    EXACTLY as non-speculative sampling — and, because the draw's key
    is a pure function of the request key and the token's stream index,
    the committed stream is bit-identical to the non-speculative
    sampled stream under the same seed.

    Multi-child tree nodes chain the same test over the packed
    children; the marginal emission law is unchanged (each rejected
    point mass removes only the mass the next test renormalizes over).

    The walk is therefore the SAME walk as the greedy rule with samples
    in place of argmaxes — this wrapper exists to carry the contract.
    """
    return accept_longest_path(pack, row_sample)


def pack_siblings(suffixes: Sequence[Sequence[int]]) -> PackedSpec:
    """Pack k sibling branches' divergent suffixes into ONE verify-shaped
    row bundle (ISSUE 20, token-tree sibling decode; SpecInfer's tree
    pointed at futures, arXiv:2305.09781).

    Every live branch must carry an EQUAL-length suffix (each gains
    exactly one token per tick, so this is an invariant, asserted):
    branch ``r``'s suffix occupies rows ``[r*s, (r+1)*s)`` in branch
    order, ``depth[r*s + j] = j`` (its RoPE offset below the frozen
    fork-point length), and the ancestor mask is per-branch
    lower-triangular — rows NEVER see another branch's rows, which is
    what lets k divergent futures share one slot's committed history.

    The bundle must fit the attention kernels' int32 bitmask packing:
    ``rows <= 32`` (the same Tq contract the pallas decode kernel
    enforces); the engine's admission fit gate guarantees it, and the
    assert here is the packer's own last line of defense.
    """
    k = len(suffixes)
    if k < 1:
        raise ValueError("pack_siblings needs >= 1 live branch")
    s = len(suffixes[0])
    if any(len(sx) != s for sx in suffixes):
        raise ValueError(
            f"sibling suffixes must be equal length, got "
            f"{[len(sx) for sx in suffixes]}"
        )
    rows = k * s
    assert rows <= 32, (
        f"sibling bundle of {k} branches x {s} tokens = {rows} rows "
        f"exceeds the 32-row tree-mask contract (admission fit gate "
        f"should have forced the fork-slot path)"
    )
    row_tokens = np.empty((rows,), np.int32)
    row_parents = np.empty((rows,), np.int32)
    depth = np.empty((rows,), np.int32)
    anc = np.zeros((rows, rows), bool)
    for r in range(k):
        o = r * s
        row_tokens[o:o + s] = np.asarray(suffixes[r], np.int32)
        depth[o:o + s] = np.arange(s, dtype=np.int32)
        row_parents[o] = -1
        row_parents[o + 1:o + s] = np.arange(o, o + s - 1, dtype=np.int32)
        anc[o:o + s, o:o + s] = np.tril(np.ones((s, s), bool))
    return PackedSpec(row_tokens=row_tokens, row_parents=row_parents,
                      depth=depth, anc=anc)


# ---------------------------------------------------------------------------
# Drafters
# ---------------------------------------------------------------------------


class Drafter:
    """The drafter interface: given a slot's full token history (prompt +
    emitted tokens, the last of which is the committed tip), propose up
    to ``k`` candidate tokens hanging off the tip. ``None`` (or an empty
    proposal) means "nothing to speculate" — the slot decodes normally
    that tick. Drafters are host-side and per-engine (not per-slot): all
    state they need is the history they are handed."""

    def propose(self, history: np.ndarray, k: int) -> Optional[DraftProposal]:
        raise NotImplementedError


class PromptLookupDrafter(Drafter):
    """Prompt-lookup decoding: n-gram match against the slot's OWN
    history. The last ``g`` tokens (longest ``g`` first) are searched for
    an earlier occurrence; the ``k`` tokens that followed that occurrence
    are proposed as a chain. Zero extra model, zero device work — the
    drafter that wins on repetitive/templated traffic (code, retrieval,
    chat boilerplate), and loses nothing elsewhere (a miss proposes
    nothing and the tick is a plain decode)."""

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1,
                 lookback: int = 1024):
        if not 1 <= ngram_min <= ngram_max:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"{ngram_min}/{ngram_max}"
            )
        if lookback < ngram_max + 1:
            raise ValueError(f"lookback too small: {lookback}")
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min
        # The match scan is O(lookback) per call and runs on the serving
        # hot path every verify tick — bound it (recent history is where
        # the repetition that accepts lives anyway).
        self.lookback = lookback

    @staticmethod
    def _matches(h: np.ndarray, g: int) -> np.ndarray:
        """Start indices p < len(h) - g with h[p:p+g] == h[-g:]."""
        n = len(h)
        if n <= g:
            return np.empty((0,), np.int64)
        win = np.lib.stride_tricks.sliding_window_view(h, g)  # (n-g+1, g)
        eq = (win[:-1] == h[n - g:]).all(axis=1)
        return np.flatnonzero(eq)

    def propose(self, history: np.ndarray, k: int) -> Optional[DraftProposal]:
        h = np.asarray(history, np.int32)[-self.lookback:]
        for g in range(self.ngram_max, self.ngram_min - 1, -1):
            starts = self._matches(h, g)
            if len(starts) == 0:
                continue
            # Most recent match whose continuation is a FULL k tokens
            # (matches near the tail cap the draft at the distance to
            # the end — on a looping stream that would freeze speculation
            # depth at 1); fall back to the most recent match otherwise.
            p = int(starts[-1])
            for q in starts[::-1]:
                if len(h) - (int(q) + g) >= k:
                    p = int(q)
                    break
            cont = h[p + g:p + g + k]
            if len(cont) == 0:
                continue
            return DraftProposal(
                tokens=cont,
                parents=np.arange(-1, len(cont) - 1, dtype=np.int32),
            )
        return None


class PromptLookupTreeDrafter(PromptLookupDrafter):
    """The tree variant of prompt lookup: when the history's n-gram
    matches continue in more than one way, propose up to ``width``
    distinct branches (most recent match first) and split the ``k``-node
    budget across them — one verify pass scores them all under the tree
    mask, and the longest accepted root path commits."""

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1,
                 width: int = 2, lookback: int = 1024):
        super().__init__(ngram_max=ngram_max, ngram_min=ngram_min,
                         lookback=lookback)
        if width < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        self.width = width

    def propose(self, history: np.ndarray, k: int) -> Optional[DraftProposal]:
        h = np.asarray(history, np.int32)[-self.lookback:]
        branches: List[np.ndarray] = []
        seen: Dict[int, bool] = {}
        for g in range(self.ngram_max, self.ngram_min - 1, -1):
            starts = self._matches(h, g)
            # Most recent context first — it gets the deepest branch.
            for p in starts[::-1]:
                cont = h[p + g:p + g + k]
                if len(cont) == 0:
                    continue
                first = int(cont[0])
                if first in seen:
                    continue
                seen[first] = True
                branches.append(cont)
                if len(branches) >= self.width:
                    break
            if len(branches) >= self.width:
                break
        if not branches:
            return None
        # Never more branches than budget (a 1-node branch is the
        # minimum spend; more would push the primary's share negative).
        branches = branches[:max(k, 1)]
        # Split the node budget: the primary branch keeps the remainder.
        per = max(k // len(branches), 1)
        lens = [per] * len(branches)
        lens[0] += k - per * len(branches)
        tokens: List[int] = []
        parents: List[int] = []
        for br, ln in zip(branches, lens):
            prev = -1
            for t in br[:ln]:
                parents.append(prev)
                prev = len(tokens)
                tokens.append(int(t))
        if not tokens:
            return None
        return DraftProposal(
            tokens=np.asarray(tokens, np.int32),
            parents=np.asarray(parents, np.int32),
        )


class DraftModelDrafter(Drafter):
    """A small draft model proposes a greedy chain — the classic two-model
    speculative setup (Leviathan et al., arXiv:2211.17192), behind the
    same interface as the free drafters. The draft runs a bucketed
    prefill (one compile per power-of-two history bucket per ``k``) and
    ``k - 1`` scanned greedy steps on its own fresh cache each call —
    stateless per call, so engine-side rollbacks need no mirroring here.
    Intended for draft models a fraction of the served model's size; the
    CPU-proxy tests use a shrunk copy."""

    def __init__(self, params, cfg):
        self.params = params
        self.cfg = cfg
        self._fns: Dict[Tuple[int, int], object] = {}

    def _build(self, bucket: int, k: int):
        import jax
        import jax.numpy as jnp

        from tree_attention_tpu.models.decode import (
            forward_step, init_cache,
        )

        cfg = self.cfg

        def run(params, padded, plen):
            cache = init_cache(cfg, 1, bucket + k)
            logits, cache = forward_step(
                params, padded, cache, cfg,
                n_tokens=jnp.asarray([0], jnp.int32) + plen,
            )
            idx = jnp.maximum(plen - 1, 0)
            tok = jnp.argmax(
                jax.lax.dynamic_index_in_dim(logits, idx, axis=1,
                                             keepdims=False), axis=-1,
            ).astype(jnp.int32)  # (1,)

            def body(carry, _):
                cache, tok = carry
                lg, cache = forward_step(params, tok[:, None], cache, cfg)
                return (cache, jnp.argmax(lg[:, -1], axis=-1)
                        .astype(jnp.int32)), tok

            (_, last), toks = jax.lax.scan(
                body, (cache, tok), None, length=k - 1
            )
            return jnp.concatenate([toks[:, 0], last])  # (k,)

        return jax.jit(run)

    def propose(self, history: np.ndarray, k: int) -> Optional[DraftProposal]:
        import jax.numpy as jnp

        h = np.asarray(history, np.int32)
        plen = len(h)
        if plen < 1 or k < 1:
            return None
        bucket = 8
        while bucket < plen:
            bucket *= 2
        fn = self._fns.get((bucket, k))
        if fn is None:
            fn = self._fns[(bucket, k)] = self._build(bucket, k)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = h
        toks = fn(self.params, jnp.asarray(padded), jnp.int32(plen))
        cont = np.asarray(toks, np.int32)
        return DraftProposal(
            tokens=cont,
            parents=np.arange(-1, len(cont) - 1, dtype=np.int32),
        )


def make_drafter(name: str, **kw) -> Drafter:
    """The CLI-facing registry: ``"ngram"`` (prompt-lookup chain, the
    zero-cost default), ``"ngram-tree"`` (its multi-branch tree variant),
    ``"model"`` (requires ``params=``/``cfg=`` of a draft model)."""
    if name == "ngram":
        return PromptLookupDrafter(**kw)
    if name == "ngram-tree":
        return PromptLookupTreeDrafter(**kw)
    if name == "model":
        if "params" not in kw or "cfg" not in kw:
            raise ValueError(
                "drafter 'model' needs params= and cfg= of a draft model"
            )
        return DraftModelDrafter(kw["params"], kw["cfg"])
    raise ValueError(
        f"unknown drafter {name!r} (expected 'ngram', 'ngram-tree' or "
        f"'model')"
    )
