"""Hardened streaming HTTP ingress for the serving engine (ISSUE 10).

The serving spine (ragged tick → chunked prefill → paged KV → prefix
cache → speculation) served pre-built synthetic traces; this module is
the real front door, and the *robustness lifecycle* is the product:

- ``POST /v1/completions`` — OpenAI-compatible shape (``prompt`` as a
  token-id list, ``max_tokens``, ``stream``), answered as an SSE token
  stream fed from the engine's one-per-tick fused token fetch (no
  per-token host sync is added: the engine's callbacks hand tokens to a
  per-request queue, the handler thread drains it).
- **Client-disconnect cancellation** — a write to a vanished client (or
  the keepalive probe between tokens) raises; the handler calls
  :meth:`SlotServer.cancel`, and the next tick's control sweep retires
  the request mid-flight: slot freed, prefix pins released, paged
  blocks unmapped back to the pool. Cancellation is cheap by
  construction — the paged allocator (arXiv:2309.06180) makes mid-
  flight retirement a host-side unmap, zero KV bytes touched.
- **Per-request deadlines** — ``deadline_s`` in the body (or the
  server's default) becomes an absolute engine deadline: expired in
  queue the request is rejected unserved, expired in flight it is
  retired with outcome ``deadline`` — work that can no longer meet its
  SLO is shed, not finished late.
- **Backpressure** — a bounded admission queue: past ``max_queue``
  waiting requests a submission gets ``429`` with ``Retry-After``
  derived from the live queue depth and the SLO monitor's windowed
  TTFT (``ceil(depth × max(ttft_p50, 50 ms) / slots)``, clamped to
  [1, 60] s): the honest estimate of when a slot-share frees up.
- **Graceful drain** — SIGTERM (via :func:`install_drain_signals`) or
  :meth:`IngressServer.drain` stops admission (new submissions get
  503), sheds the queued backlog, finishes in-flight requests, and
  lets ``serve()`` return so the process exits through its normal
  telemetry flush.

Threading contract: handler threads never touch engine state directly —
they go through exactly three thread-safe seams
(:meth:`QueueRequestSource.submit`, :meth:`SlotServer.cancel`,
:meth:`SlotServer.request_drain`), all mailboxes the tick loop sweeps at
tick start, so every actual engine mutation stays on the engine thread.
Ingress-local shared state is mutated only under ``self._lock`` — the
invariant linter's lock-safety pass scopes this file.
"""

from __future__ import annotations

import itertools
import json
import math
import queue
import threading
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional

import numpy as np

from tree_attention_tpu import obs
from tree_attention_tpu.serving.engine import (
    OUTCOME_BUDGET,
    OUTCOME_EOS,
    Request,
    RequestResult,
    RequestSource,
    ServeReport,
    SlotServer,
)
from tree_attention_tpu.utils.httpd import DaemonHTTPServer
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.ingress")

# Request uids are minted process-wide, not per ingress: co-located
# replicas (LocalReplica fleets, disagg pairs under one router) all feed
# the one process-global request ledger, which keys by uid — per-ingress
# counters would collide there and silently drop ledgers (ISSUE 16).
# `next()` on an itertools.count is atomic under the GIL.
_UID_COUNTER = itertools.count()

# Ingress-plane metrics: HTTP outcomes by route/code (backpressure 429s
# and drain 503s live here — they never became engine requests), SSE
# disconnect detections, and the live admission-queue depth the
# Retry-After formula reads.
_HTTP_REQUESTS = obs.counter(
    "serving_http_requests_total",
    "ingress HTTP requests answered, by route and status code",
    labels=("route", "code"),
)
_DISCONNECTS = obs.counter(
    "serving_sse_disconnects_total",
    "SSE streams whose client vanished mid-stream (each cancels its "
    "request)",
)
_QUEUE_DEPTH = obs.gauge(
    "serving_ingress_queue_depth",
    "requests submitted to the ingress but not yet streaming tokens",
)

#: Engine outcome -> OpenAI-ish finish_reason. The happy arcs use the
#: OpenAI vocabulary; the robustness arcs keep the engine's names — a
#: client that asked for a deadline should see "deadline", not a lie.
FINISH_REASONS = {OUTCOME_EOS: "stop", OUTCOME_BUDGET: "length"}

_RETRY_AFTER_MIN_TTFT_S = 0.05
_RETRY_AFTER_MAX_S = 60


class QueueRequestSource(RequestSource):
    """Thread-safe live feeder: HTTP handlers submit, the tick loop polls.

    ``self._lock`` is a :class:`threading.Condition`: :meth:`submit`
    notifies, :meth:`wait` blocks the idle engine until work (or close)
    arrives — the loop never spins while the server sits idle.
    """

    def __init__(self):
        self._lock = threading.Condition()
        self._queue: List[Request] = []
        self._closed = False

    def submit(self, req: Request) -> bool:
        """Queue one request (any thread); False once closed (draining).
        Stamps ``visible_at`` so the engine's queue-wait/TTFT clocks
        start now, not at the loop's next poll."""
        import time

        with self._lock:
            if self._closed:
                return False
            req.visible_at = time.monotonic()
            self._queue.append(req)
            self._lock.notify_all()
            return True

    def poll(self, tick: int) -> List[Request]:
        with self._lock:
            out = self._queue
            self._queue = []
        for r in out:
            # Live requests have no synthetic arrival time; the tick the
            # loop first saw them keeps results/report ordering sane.
            r.arrival_tick = tick
        return out

    def wait(self, timeout: float) -> bool:
        with self._lock:
            if self._queue or self._closed:
                return True
            self._lock.wait(timeout)
            return bool(self._queue)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    @property
    def exhausted(self) -> bool:
        with self._lock:
            return self._closed and not self._queue


class IngressServer(DaemonHTTPServer):
    """The serving front door: one engine thread, N handler threads.

    Args:
      engine: the :class:`SlotServer` to serve from. :meth:`start` spawns
        the engine's tick loop on a dedicated thread against a
        :class:`QueueRequestSource`; :meth:`drain` (or SIGTERM via
        :func:`install_drain_signals`) winds it down gracefully.
      max_queue: bound on requests admitted-but-not-yet-streaming; past
        it submissions get 429 + Retry-After (the backpressure seam).
      default_deadline_s: deadline applied to requests that do not carry
        their own ``deadline_s`` (None = no default — requests wait
        forever).
      default_max_tokens: ``max_tokens`` for bodies that omit it.
      keepalive_s: seconds between SSE keepalive comments while no token
        is ready — the probe that detects vanished clients even when the
        engine is between tokens.
    """

    thread_name = "serving-ingress"

    def __init__(
        self,
        engine: SlotServer,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        max_queue: int = 64,
        default_deadline_s: Optional[float] = None,
        default_max_tokens: int = 16,
        keepalive_s: float = 0.5,
    ):
        super().__init__(port, host)
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.engine = engine
        self.max_queue = max_queue
        self.default_deadline_s = default_deadline_s
        self.default_max_tokens = default_max_tokens
        self.keepalive_s = keepalive_s
        self.source = QueueRequestSource()
        # Reentrant: drain() runs inside the SIGTERM/SIGINT handler on
        # the main thread, which may be interrupted while holding this
        # lock (join()'s bookkeeping) — a plain Lock would self-deadlock
        # the drain, the exact failure mode the obs crash-path rule
        # exists for.
        self._lock = threading.RLock()
        self._queued = 0  # submitted, first token not yet streamed
        self._draining = False
        self._engine_thread: Optional[threading.Thread] = None
        self._report: Optional[ServeReport] = None
        self._engine_error: Optional[BaseException] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> int:
        port = super().start()
        with self._lock:
            if self._engine_thread is None:
                self._engine_thread = threading.Thread(
                    target=self._run_engine,
                    name="serving-engine",
                    daemon=True,
                )
                self._engine_thread.start()
        log.info("serving ingress: http://%s:%d/v1/completions",
                 self._host, port)
        return port

    def _run_engine(self) -> None:
        try:
            report = self.engine.serve(self.source)
        except BaseException as e:
            log.exception("engine loop crashed; ingress is dead")
            with self._lock:
                self._engine_error = e
            return
        with self._lock:
            self._report = report

    def drain(self) -> None:
        """Graceful shutdown, phase one (thread-safe, idempotent): stop
        admitting (new POSTs get 503), shed the queued backlog, let
        in-flight requests finish. The engine loop exits once drained;
        :meth:`join` collects its report."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        log.info("ingress drain: admission stopped, finishing in-flight")
        self.engine.request_drain()

    def join(self, timeout: Optional[float] = None) -> Optional[ServeReport]:
        """Wait for the engine loop to drain; returns its ServeReport
        (None if still running at ``timeout``)."""
        with self._lock:
            t = self._engine_thread
        if t is not None:
            t.join(timeout)
        with self._lock:
            return self._report

    def stop(self) -> None:
        """Drain, collect the engine, then tear the HTTP server down."""
        self.drain()
        self.join(timeout=60.0)
        super().stop()

    @property
    def report(self) -> Optional[ServeReport]:
        with self._lock:
            return self._report

    @property
    def engine_error(self) -> Optional[BaseException]:
        """The exception that killed the engine loop, if any (callers
        deciding an exit code must not mistake a crash for a drain)."""
        with self._lock:
            return self._engine_error

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._queued

    # -- routing ----------------------------------------------------------

    def handle(self, method: str, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if method == "POST" and path == "/v1/completions":
            self._completions(req)
        elif method == "POST" and path == "/admin/drain":
            # The fleet's drain handshake (ISSUE 11): a router/supervisor
            # starts this replica's graceful drain remotely — idempotent,
            # answered before the drain completes (poll /ingress/stats
            # for engine_done).
            self.drain()
            self._reply_counted(req, "drain", 202,
                                json.dumps({"draining": True}),
                                "application/json")
        elif method == "GET" and path == "/ingress/stats":
            self._reply_counted(req, "stats", 200,
                                json.dumps(self._stats(), indent=2),
                                "application/json")
        elif method == "GET" and path == "/":
            self._reply_counted(
                req, "help", 200,
                "tree_attention_tpu serving ingress: "
                "POST /v1/completions  GET /ingress/stats\n",
                "text/plain",
            )
        else:
            self._reply_counted(req, "other", 404,
                                f"no such endpoint: {method} {path}\n",
                                "text/plain")

    def _engine_alive(self) -> bool:
        """The engine loop is up and has not crashed — the handler
        watchdogs' liveness gate (a healthy engine legitimately goes
        silent for long stretches, e.g. a best-of family holding its
        streams until the join)."""
        with self._lock:
            return (self._engine_thread is not None
                    and self._engine_thread.is_alive()
                    and self._engine_error is None)

    def _stats(self) -> Dict[str, Any]:
        alive = self._engine_alive()
        with self._lock:
            out = {
                "queue_depth": self._queued,
                "max_queue": self.max_queue,
                "draining": self._draining,
                "engine_done": self._report is not None,
                "engine_alive": alive,
                # The rejoin handshake's readiness verdict: a router may
                # route here iff the replica is admitting (the engine loop
                # is up and not draining).
                "ready": alive and not self._draining,
            }
        out["slots"] = self.engine.slots
        out["goodput"] = round(self.engine.slo.goodput(), 4)
        return out

    def _reply_counted(self, req, route: str, code: int, body: str,
                       ctype: str, headers: Optional[dict] = None) -> None:
        if obs.REGISTRY.enabled:
            _HTTP_REQUESTS.labels(route=route, code=str(code)).inc()
        self.reply(req, code, body, ctype, headers)

    # -- the completions endpoint ------------------------------------------

    def _completions(self, req: BaseHTTPRequestHandler) -> None:
        import time

        body, err = self._parse_body(req)
        if err is not None:
            self._reply_counted(req, "completions", 400,
                                _error_json(err), "application/json")
            return
        # Admission control BEFORE any engine state is touched: drain
        # beats backpressure beats service.
        with self._lock:
            if self._draining:
                depth, verdict = self._queued, 503
            elif self._queued >= self.max_queue:
                depth, verdict = self._queued, 429
            else:
                self._queued += 1
                depth, verdict = self._queued, 200
                uid = next(_UID_COUNTER)
        if verdict == 503:
            self._reply_counted(
                req, "completions", 503,
                _error_json("server is draining; not accepting requests"),
                "application/json",
            )
            return
        if verdict == 429:
            retry = self._retry_after(depth)
            self._reply_counted(
                req, "completions", 429,
                _error_json(
                    f"admission queue full ({depth} waiting); retry in "
                    f"~{retry}s", type="overloaded"),
                "application/json",
                headers={"Retry-After": retry},
            )
            return
        if obs.REGISTRY.enabled:
            _QUEUE_DEPTH.set(depth)

        # Trace context (ISSUE 16): adopt the client's W3C traceparent
        # when one arrives (the router relays its own — replica spans
        # join the fleet trace), mint a fresh one otherwise (direct
        # clients get a trace too). The pair rides the Request through
        # admission, disagg handoff, and retirement.
        parsed = obs.parse_traceparent(
            req.headers.get(obs.TRACEPARENT_HEADER, ""))
        adopted = parsed is not None
        if parsed is None:
            parsed = (obs.new_trace_id(), obs.new_span_id())
        trace_id, parent_span = parsed

        events: "queue.Queue" = queue.Queue()
        deadline = body.get("deadline_s", self.default_deadline_s)
        # How many per-branch finish events end the stream: n parallel
        # completions, or ONE for best_of (the engine streams only the
        # selected winner, as branch 0). Extra branches a mid-generation
        # fork(uid)/fork_at adds stream tagged by their index but never
        # gate the close.
        best_of = body.get("best_of")
        n_expected = 1 if (best_of or 0) > 1 else body["n"]
        request = Request(
            uid=uid,
            prompt=np.asarray(body["prompt"], np.int32),
            max_new_tokens=body["max_tokens"],
            eos_id=body.get("eos_id"),
            deadline_s=(time.monotonic() + deadline
                        if deadline is not None else None),
            n=body["n"],
            best_of=best_of,
            temperature=body.get("temperature"),
            top_k=body.get("top_k"),
            seed=body.get("seed"),
            fork_at=body.get("fork_at"),
            trace=(trace_id, parent_span),
            on_branch_token=lambda i, t: events.put(("token", (i, t))),
            on_branch_finish=lambda i, res: events.put(
                ("finish", (i, res))),
        )
        # Idempotent TTFT-phase exit: whichever comes first — first
        # token, finish, or a disconnect — releases exactly one unit of
        # admission-queue depth.
        deq_state = [False]

        def dequeue_once() -> None:
            if not deq_state[0]:
                deq_state[0] = True
                self._dequeued()

        if obs.TRACER.active:
            # A named submit slice anchors the flow point: Perfetto
            # binds flow arrows to the slice enclosing their timestamp.
            # "s" starts a new flow chain (direct client, trace minted
            # here); "t" is a step on the chain the upstream hop (the
            # router's relay span) already started.
            with obs.span("ingress_submit", cat="serving",
                          args={"rid": uid, "trace_id": trace_id,
                                "adopted": adopted}):
                obs.flow("t" if adopted else "s", obs.flow_id(trace_id))
                submitted = self.source.submit(request)
        else:
            submitted = self.source.submit(request)
        if not submitted:
            dequeue_once()
            self._reply_counted(
                req, "completions", 503,
                _error_json("server is draining; not accepting requests"),
                "application/json",
            )
            return
        try:
            if body.get("stream", True):
                self._stream_sse(req, uid, events, dequeue_once,
                                 n_expected)
            else:
                self._respond_whole(req, uid, events, dequeue_once,
                                    n_expected)
        except BaseException as e:
            # ANY handler failure — a vanished client (the disconnect
            # arc the chaos harness storms: BrokenPipe/ConnectionReset/
            # ConnectionAborted/timeouts), or an unexpected bug — must
            # cancel the engine request and release its admission-queue
            # unit, or max_queue such failures would brick the server
            # with 429s while the engine sits idle.
            if isinstance(e, OSError):
                _DISCONNECTS.inc()
            else:
                log.exception("completions handler failed (rid %d)", uid)
            self.engine.cancel(uid)
            dequeue_once()
            self._drain_events(events, n_expected)
            raise  # DaemonHTTPServer swallows the socket kinds

    def _parse_body(self, req: BaseHTTPRequestHandler):
        try:
            n = int(req.headers.get("Content-Length", 0))
            body = json.loads(req.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            return None, f"unreadable JSON body: {e}"
        prompt = body.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in prompt)):
            return None, (
                "body.prompt must be a non-empty list of token ids (this "
                "model serves token ids; there is no tokenizer in the "
                "loop)"
            )
        if not all(-(1 << 31) <= t < (1 << 31) for t in prompt):
            # Checked HERE so the int32 conversion after admission
            # accounting can never raise (NumPy >= 2.0 overflows loudly).
            return None, "body.prompt token ids must fit int32"
        # Coerce every numeric field HERE, before any admission-queue
        # accounting: a malformed field after the queue unit is taken
        # would leak depth on its way out (the brick-the-server class).
        try:
            body["max_tokens"] = int(body.get("max_tokens",
                                              self.default_max_tokens))
            if body.get("deadline_s") is not None:
                body["deadline_s"] = float(body["deadline_s"])
            if body.get("eos_id") is not None:
                body["eos_id"] = int(body["eos_id"])
            # Sampling + fork-family fields (ISSUE 15, OpenAI-shaped):
            # n parallel completions, best_of server-side selection,
            # temperature/top_k/seed sampling overrides, fork_at for
            # replayable mid-generation branches.
            body["n"] = int(body.get("n", 1))
            if body.get("best_of") is not None:
                body["best_of"] = int(body["best_of"])
            if body.get("temperature") is not None:
                body["temperature"] = float(body["temperature"])
            if body.get("top_k") is not None:
                body["top_k"] = int(body["top_k"])
            if body.get("seed") is not None:
                body["seed"] = int(body["seed"])
            if body.get("fork_at") is not None:
                body["fork_at"] = int(body["fork_at"])
        except (TypeError, ValueError) as e:
            return None, (f"non-numeric max_tokens/deadline_s/eos_id/"
                          f"n/best_of/temperature/top_k/seed/fork_at: {e}")
        if body["n"] < 1:
            return None, "body.n must be >= 1"
        if body.get("best_of") is not None and body["best_of"] < 1:
            return None, "body.best_of must be >= 1"
        if (body.get("best_of") or 0) > 1 and body["n"] != 1:
            return None, ("body.best_of runs server-side selection and "
                          "streams ONE winner — it requires n == 1")
        if body.get("temperature") is not None and body["temperature"] < 0:
            return None, "body.temperature must be >= 0"
        if body.get("top_k") is not None and body["top_k"] < 0:
            return None, "body.top_k must be >= 0 (0 = off)"
        if body.get("fork_at") is not None and body["fork_at"] < 1:
            return None, "body.fork_at must be >= 1"
        return body, None

    def _retry_after(self, depth: int) -> int:
        """Seconds until a slot-share plausibly frees: queue depth times
        the SLO window's observed TTFT p50 (floor 50 ms before any
        sample exists), divided by the slots draining in parallel."""
        ttft = max(self.engine.slo.snapshot().get("ttft_p50_s", 0.0),
                   _RETRY_AFTER_MIN_TTFT_S)
        est = math.ceil(depth * ttft / max(self.engine.slots, 1))
        return max(1, min(est, _RETRY_AFTER_MAX_S))

    def _dequeued(self) -> None:
        """One request left the TTFT phase (first token, or finished
        without one)."""
        with self._lock:
            self._queued -= 1
            depth = self._queued
        if obs.REGISTRY.enabled:
            _QUEUE_DEPTH.set(depth)

    @staticmethod
    def _drain_events(events: "queue.Queue", n_expected: int = 1) -> None:
        """After a disconnect: keep draining callback events until the
        engine retires every branch of the request, so the queue (and
        the Request the engine still holds) can be collected."""
        seen = 0
        while True:
            try:
                kind, payload = events.get(timeout=30.0)
            except queue.Empty:
                return  # engine gone/wedged; nothing more to free
            if kind == "finish":
                idx, _ = payload
                if idx < n_expected:
                    seen += 1
                    if seen >= n_expected:
                        return

    # -- response writers --------------------------------------------------

    def _stream_sse(self, req: BaseHTTPRequestHandler, uid: int,
                    events: "queue.Queue", dequeue_once,
                    n_expected: int = 1) -> None:
        """SSE token stream: one ``data:`` event per committed token
        (``choices[].index`` tags the branch — n>1 completions
        interleave on ONE stream, the OpenAI shape), one finish event
        per branch, then ``[DONE]`` once all ``n_expected`` branches
        finished. Keepalive comments between tokens probe for vanished
        clients; ~30 s of total silence from a DEAD engine thread
        (crashed or exited) cancels with an error finish — a connected
        client must not hold an admission-queue unit against a dead
        engine forever. A LIVE engine may legitimately go silent far
        longer (a best-of family streams nothing until its join), so
        silence alone never cancels; the server-side bound there is
        the request's own deadline_s."""
        if obs.REGISTRY.enabled:
            _HTTP_REQUESTS.labels(route="completions", code="200").inc()
        req.send_response(200)
        req.send_header("Content-Type", "text/event-stream")
        req.send_header("Cache-Control", "no-cache")
        req.end_headers()
        silent = 0
        finished = 0
        while True:
            try:
                kind, payload = events.get(timeout=self.keepalive_s)
            except queue.Empty:
                silent += 1
                if silent * self.keepalive_s >= 30.0 \
                        and not self._engine_alive():
                    self.engine.cancel(uid)
                    dequeue_once()
                    req.wfile.write(b"data: " + json.dumps(
                        {"error": {
                            "message": "engine unresponsive; "
                                       "request cancelled",
                            "type": "server_error",
                        }}).encode() + b"\n\n")  # one line: SSE framing
                    req.wfile.write(b"data: [DONE]\n\n")
                    req.wfile.flush()
                    return
                # No token ready: probe the socket so a vanished client
                # is detected even while its request sits in prefill.
                req.wfile.write(b": keepalive\n\n")
                req.wfile.flush()
                continue
            silent = 0
            if kind == "token":
                idx, tok = payload
                dequeue_once()
                req.wfile.write(_sse_token(uid, tok, idx))
                req.wfile.flush()
            else:
                idx, result = payload
                result: RequestResult
                dequeue_once()
                req.wfile.write(_sse_finish(uid, result, idx))
                if idx < n_expected:
                    finished += 1
                if finished >= n_expected:
                    req.wfile.write(b"data: [DONE]\n\n")
                    req.wfile.flush()
                    return
                req.wfile.flush()

    def _respond_whole(self, req: BaseHTTPRequestHandler, uid: int,
                       events: "queue.Queue", dequeue_once,
                       n_expected: int = 1) -> None:
        """``stream: false``: block until every branch finishes, answer
        with one JSON body (``choices`` sorted by index — the OpenAI
        n>1 shape). The wait is bounded per EVENT (tokens reset it):
        30 s of silence from a DEAD engine thread cancels with a 503
        rather than hang the handler (and its admission-queue unit)
        forever — a LIVE engine may legitimately be silent that long
        (a best-of family emits nothing until its join), so silence
        alone keeps waiting; deadline_s is the server-side bound
        there."""
        finished: List[RequestResult] = []
        while len(finished) < n_expected:
            try:
                kind, payload = events.get(timeout=30.0)
            except queue.Empty:
                if self._engine_alive():
                    continue  # quiet but healthy — keep waiting
                self.engine.cancel(uid)
                dequeue_once()
                self._reply_counted(
                    req, "completions", 503,
                    _error_json("engine unresponsive; request cancelled",
                                type="server_error"),
                    "application/json",
                )
                return
            if kind == "token":
                # Same TTFT-phase semantics as the SSE path: a
                # generating request occupies a slot, not the
                # admission queue.
                dequeue_once()
                continue
            idx, result = payload
            if idx < n_expected:
                finished.append(result)
        dequeue_once()
        finished.sort(key=lambda r: r.index)
        best = finished[0]
        # The per-request cost ledger (ISSUE 16) closes with the FIRST
        # branch the engine retires; later branches of an n>1 family
        # carry None.
        ledger = next(
            (r.ledger for r in finished if r.ledger is not None), None)
        code = 200 if any(
            r.tokens or FINISH_REASONS.get(r.outcome, r.outcome)
            in ("stop", "length") for r in finished
        ) else 503
        self._reply_counted(req, "completions", code, json.dumps({
            "id": f"cmpl-{uid}",
            "object": "text_completion",
            "choices": [{
                "index": r.index,
                "text": _render(r.tokens),
                "token_ids": list(r.tokens),
                "finish_reason": FINISH_REASONS.get(r.outcome, r.outcome),
            } for r in finished],
            "usage": {
                "prompt_tokens": best.prompt_len,
                "completion_tokens": sum(len(r.tokens) for r in finished),
                "prefix_hit_tokens": best.prefix_hit_tokens,
                **({"ledger": ledger} if ledger is not None else {}),
            },
        }, indent=2), "application/json")


# -- SSE wire helpers -------------------------------------------------------


def _render(tokens) -> str:
    """Token ids as text — space-separated ids (no tokenizer exists in
    this stack; honest rendering beats pretending)."""
    return " ".join(str(int(t)) for t in tokens)


def _sse_token(uid: int, tok: int, index: int = 0) -> bytes:
    return ("data: " + json.dumps({
        "id": f"cmpl-{uid}",
        "object": "text_completion",
        "choices": [{
            "index": index,
            "text": f"{int(tok)} ",
            "token_ids": [int(tok)],
            "finish_reason": None,
        }],
    }) + "\n\n").encode()


def _sse_finish(uid: int, result: RequestResult,
                index: int = 0) -> bytes:
    return ("data: " + json.dumps({
        "id": f"cmpl-{uid}",
        "object": "text_completion",
        "choices": [{
            "index": index,
            "text": "",
            "token_ids": [],
            "finish_reason": FINISH_REASONS.get(result.outcome,
                                                result.outcome),
        }],
        "usage": {
            "prompt_tokens": result.prompt_len,
            "completion_tokens": len(result.tokens),
            # Replica-side hit/miss report (ISSUE 11): how much of this
            # prompt the replica's radix cache actually served — the
            # router's approximate-tree feedback signal.
            "prefix_hit_tokens": result.prefix_hit_tokens,
            # Per-request cost ledger (ISSUE 16); present only when the
            # ledger is armed, and only on the branch that closed it.
            **({"ledger": result.ledger}
               if result.ledger is not None else {}),
        },
    }) + "\n\n").encode()


def _error_json(message: str, type: str = "invalid_request") -> str:
    return json.dumps({"error": {"message": message, "type": type}},
                      indent=2)


def install_drain_signals(server: IngressServer) -> None:
    """SIGTERM/SIGINT → graceful drain (main thread only).

    Replaces the obs crash handler's flush-then-die SIGTERM for the
    serving process: the drain lets in-flight requests finish, the
    engine loop returns, and the process exits through its normal
    telemetry flush (the CLI's ``finally``/atexit path) — stop
    admitting, finish in-flight, flush telemetry, in that order. A
    second signal while draining falls back to the previous handler
    (an operator's double-SIGTERM must still kill a stuck drain).
    """
    import signal

    prev = {}

    def _begin_drain(signum, frame):
        if server.draining:
            # Second signal while draining: escalate — an operator's
            # kill must stay a kill even if the drain is stuck. A
            # callable previous handler runs; otherwise restore the
            # default disposition and re-raise the signal.
            handler = prev.get(signum)
            if callable(handler):
                handler(signum, frame)
            else:
                signal.signal(signum, signal.SIG_DFL)
                import os

                os.kill(os.getpid(), signum)
            return
        server.drain()

    for sig in (signal.SIGTERM, signal.SIGINT):
        prev[sig] = signal.signal(sig, _begin_drain)
