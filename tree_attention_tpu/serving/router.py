"""Cache-aware HTTP router for a fleet of replica ingresses (ISSUE 11).

One engine behind one ingress serves one machine's worth of traffic; the
ROADMAP's millions-of-users direction needs N of them — and naive
round-robin over N replicas dilutes the radix prefix cache's TTFT win by
1/N, because a prompt's shared prefix lands on a different replica's
tree every time. SGLang's cache-aware routing (arXiv:2312.07104) is the
fix this module implements:

- **Approximate merged radix tree** — the router keeps one
  :class:`ReplicaTree` per replica, a block-granular radix tree over the
  prompts it has routed there. It is *approximate by design*: the router
  never sees the replica's pool, only its own routing history plus the
  replica's per-request hit report (``usage.prefix_hit_tokens`` in the
  completion response — the telemetry the ingress publishes exactly for
  this). A report of fewer hit tokens than the tree predicted means the
  replica evicted that path: the router truncates its tree to match
  (staleness is corrected by feedback, not guessed at). LRU caps and an
  optional TTL bound the tree when feedback is sparse.
- **Affinity with hysteresis** — each request scores every routable
  replica by longest-prefix match; the best match wins *unless* that
  replica's in-flight load exceeds the fleet minimum by more than
  ``hysteresis`` requests, in which case least-loaded wins (one hot
  prefix must not starve a replica while its peers idle). Cold prompts
  go least-loaded with a round-robin tie-break, and the chosen replica's
  tree learns the prompt either way — the next sharer routes with
  affinity.
- **Failover and drain requeue** — a replica that refuses (503: it is
  draining or its engine died) or sheds a queued request before any
  token streamed is not an error the client sees: the router re-routes
  the request to a peer (reason ``failover``) with its deadline budget
  reduced by the time already spent. This is what turns the per-replica
  SIGTERM drain into rolling-restart-without-drops — the drained
  replica's queued work lands on its peers, in-flight streams finish
  where they are.
- **Metrics federation** — ``GET /metrics`` serves the router's own
  registry plus every replica's scrape (replicas registered with a
  ``metrics_url``) rewritten under a ``replica="<name>"`` label, so one
  Prometheus target sees the whole fleet.
- **Telemetry federation** (ISSUE 16) — ``GET /requests`` merges the
  request-ledger snapshots (the router process's own — which is where
  in-process replicas record — labeled ``replica="local"``, plus every
  replica obs endpoint derived from its ``metrics_url``), each entry
  gaining a ``replica`` label; ``GET /healthz`` rolls up tick liveness
  so a WEDGED replica (engine loop stopped, HTTP thread still
  answering) fails the FLEET check, not just its own process's; ``GET
  /flight`` returns the router's ring plus every reachable replica's.
- **Trace propagation** (ISSUE 16) — the router is the fleet's trace
  ingress: it adopts the client's W3C ``traceparent`` (or mints one),
  forwards it on the relayed POST with the routing span as parent, and
  emits the Chrome-trace flow *start* point inside its routing slice —
  the replica's ingress/engine/disagg hops add step points and the
  retire seam finishes the arrow, so ONE Perfetto load of the merged
  per-process trace files shows router → replica → workers connected.

The router is a *pass-through*: it speaks the same OpenAI-compatible
``POST /v1/completions`` shape as the ingress and relays SSE events
byte-for-byte (tokens are never re-framed), so routed streams are
token-identical to direct replica serving — the fleet bench asserts it.

Threading contract: handler threads share the replica registry, the
approximate trees, and the in-flight counters; every mutation happens
under ``self._lock`` (an RLock — the invariant linter's lock-safety
pass scopes this file). Replica HTTP I/O happens *outside* the lock.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Set, Tuple

from tree_attention_tpu import obs
from tree_attention_tpu.utils.httpd import DaemonHTTPServer
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.router")

#: Routing reasons — the label vocabulary of serving_router_requests_total.
REASON_AFFINITY = "affinity"          # longest-prefix match won
REASON_LEAST_LOADED = "least_loaded"  # cold prompt / hysteresis fallback
REASON_FAILOVER = "failover"          # re-route after a replica refused

_ROUTED = obs.counter(
    "serving_router_requests_total",
    "requests routed, by replica and routing reason "
    "(affinity | least_loaded | failover)",
    labels=("replica", "reason"),
)
_AFFINITY_HITS = obs.counter(
    "serving_router_prefix_affinity_hits_total",
    "affinity-routed requests whose replica confirmed a prefix-cache hit "
    "(usage.prefix_hit_tokens > 0) — the router's bet, paid off",
)
_REPLICA_HEALTHY = obs.gauge(
    "serving_router_replica_healthy",
    "1 while the replica is routable (up, not draining), else 0",
    labels=("replica",),
)
_REPLICA_INFLIGHT = obs.gauge(
    "serving_router_replica_inflight",
    "requests this router currently has streaming from the replica",
    labels=("replica",),
)


class ReplicaTree:
    """Approximate radix tree over the prompts routed to ONE replica.

    Block-granular like the engine's real tree (a partial block can
    never be a cache hit replica-side, so the router scores in the same
    units), but with none of the pool machinery: nodes carry only a
    last-use stamp. Bounded two ways — an LRU node cap (``max_blocks``)
    and an optional ``ttl_s`` after which untouched subtrees decay — and
    corrected by replica feedback (:meth:`truncate`).

    NOT thread-safe on its own: the router mutates it under its lock.
    """

    def __init__(self, block: int = 16, max_blocks: int = 2048,
                 ttl_s: Optional[float] = None):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if max_blocks < 1:
            raise ValueError(f"max_blocks must be >= 1, got {max_blocks}")
        self.block = block
        self.max_blocks = max_blocks
        self.ttl_s = ttl_s
        # node = {key: [children-dict, last_use]} rooted at self._root.
        self._root: Dict[Tuple[int, ...], List[Any]] = {}
        self._count = 0

    @property
    def blocks(self) -> int:
        """Nodes (= full prompt blocks) currently tracked."""
        return self._count

    def _keys(self, tokens) -> List[Tuple[int, ...]]:
        toks = [int(t) for t in tokens]
        nb = len(toks) // self.block
        return [tuple(toks[j * self.block:(j + 1) * self.block])
                for j in range(nb)]

    def match(self, tokens) -> int:
        """Longest tracked prefix of ``tokens``, in tokens (full blocks)."""
        level = self._root
        matched = 0
        for key in self._keys(tokens):
            ent = level.get(key)
            if ent is None:
                break
            matched += self.block
            level = ent[0]
        return matched

    def insert(self, tokens, now: float) -> None:
        """Track the prompt's full blocks (touches the whole path)."""
        level = self._root
        for key in self._keys(tokens):
            ent = level.get(key)
            if ent is None:
                ent = [{}, now]
                level[key] = ent
                self._count += 1
            else:
                ent[1] = now
            level = ent[0]
        while self._count > self.max_blocks:
            if not self._evict_lru_leaf():
                break

    def truncate(self, tokens, keep_tokens: int) -> None:
        """Replica feedback: it only matched ``keep_tokens`` of this
        prompt, so everything the tree tracks past that point (along
        this path) is stale — drop the subtree there."""
        keep_blocks = max(keep_tokens, 0) // self.block
        keys = self._keys(tokens)
        if keep_blocks >= len(keys):
            return
        level = self._root
        for key in keys[:keep_blocks]:
            ent = level.get(key)
            if ent is None:
                return  # path already gone
            level = ent[0]
        ent = level.get(keys[keep_blocks])
        if ent is not None:
            self._count -= 1 + self._size(ent[0])
            del level[keys[keep_blocks]]

    def decay(self, now: float) -> int:
        """Drop subtrees untouched for ``ttl_s`` (no-op when ttl is off);
        returns nodes dropped. Stale affinity is worse than no affinity —
        it routes a request to a replica whose cache moved on."""
        if self.ttl_s is None:
            return 0
        dropped = self._decay_level(self._root, now)
        self._count -= dropped
        return dropped

    def clear(self) -> None:
        """Forget everything (a restarted replica's cache is empty)."""
        self._root = {}
        self._count = 0

    def _decay_level(self, level: Dict, now: float) -> int:
        dropped = 0
        for key in list(level):
            children, last_use = level[key]
            if now - last_use > self.ttl_s:
                dropped += 1 + self._size(children)
                del level[key]
            else:
                dropped += self._decay_level(children, now)
        return dropped

    def _size(self, level: Dict) -> int:
        return sum(1 + self._size(ent[0]) for ent in level.values())

    def _evict_lru_leaf(self) -> bool:
        """Drop the least-recently-used LEAF (interior nodes are live
        prefixes of their children — same rule as the engine's tree)."""
        best: Optional[Tuple[Dict, Tuple[int, ...]]] = None
        best_use = math.inf
        stack = [self._root]
        while stack:
            level = stack.pop()
            for key, (children, last_use) in level.items():
                if children:
                    stack.append(children)
                elif last_use < best_use:
                    best, best_use = (level, key), last_use
        if best is None:
            return False
        del best[0][best[1]]
        self._count -= 1
        return True


@dataclasses.dataclass
class _Replica:
    """Router-side view of one replica ingress."""

    name: str
    host: str
    port: int
    metrics_url: Optional[str] = None
    state: str = "up"  # up | draining | down

    @property
    def routable(self) -> bool:
        return self.state == "up"


class FleetRouter(DaemonHTTPServer):
    """The fleet front door: affinity-routed pass-through proxy.

    Args:
      block: prefix granularity of the approximate trees — MUST equal
        the replicas' ``--prefix-block`` (scores in any other unit would
        promise hits the replicas cannot deliver).
      affinity: route by longest-prefix match (False = pure least-loaded
        with round-robin tie-break — the dilution baseline the fleet
        bench measures against).
      hysteresis: max in-flight excess (over the fleet minimum) an
        affinity pick may carry before least-loaded overrides it.
      min_match: smallest prefix match (tokens) worth routing on
        (default: one block).
      max_tree_blocks / tree_ttl_s: per-replica tree bounds.
      replica_timeout_s: read timeout on replica connections (the
        ingress's SSE keepalives tick faster than this unless the
        replica process is gone).
    """

    thread_name = "serving-router"

    def __init__(
        self,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        block: int = 16,
        affinity: bool = True,
        hysteresis: int = 2,
        min_match: Optional[int] = None,
        max_tree_blocks: int = 2048,
        tree_ttl_s: Optional[float] = None,
        replica_timeout_s: float = 60.0,
    ):
        super().__init__(port, host)
        if hysteresis < 0:
            raise ValueError(f"hysteresis must be >= 0, got {hysteresis}")
        self.block = block
        self.affinity = affinity
        self.hysteresis = hysteresis
        self.min_match = block if min_match is None else min_match
        self.max_tree_blocks = max_tree_blocks
        self.tree_ttl_s = tree_ttl_s
        self.replica_timeout_s = replica_timeout_s
        self._lock = threading.RLock()
        self._replicas: Dict[str, _Replica] = {}
        self._trees: Dict[str, ReplicaTree] = {}
        self._inflight: Dict[str, int] = {}
        self._rr = 0  # round-robin cursor for least-loaded ties
        self._last_decay = 0.0  # TTL sweeps are periodic, not per-route
        self._routed = {REASON_AFFINITY: 0, REASON_LEAST_LOADED: 0,
                        REASON_FAILOVER: 0}
        self._requeued = 0   # shed/refused work replayed onto a peer
        self._dropped = 0    # accepted work the router could NOT save

    # -- replica registry (the fleet supervisor's seam) -------------------

    def add_replica(self, name: str, port: int, *,
                    host: str = "127.0.0.1",
                    metrics_url: Optional[str] = None) -> None:
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = _Replica(name, host, port, metrics_url)
            self._trees[name] = ReplicaTree(
                block=self.block, max_blocks=self.max_tree_blocks,
                ttl_s=self.tree_ttl_s,
            )
            self._inflight[name] = 0
        self._publish_health(name, True)

    def set_draining(self, name: str) -> None:
        """Stop routing NEW work to the replica (rolling-restart phase
        one); its in-flight streams keep relaying."""
        with self._lock:
            self._replicas[name].state = "draining"
        self._publish_health(name, False)

    def mark_down(self, name: str) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.state == "down":
                return
            rep.state = "down"
        self._publish_health(name, False)
        log.warning("router: replica %s marked down", name)

    def rejoin(self, name: str, *, port: Optional[int] = None,
               reset_tree: bool = True) -> None:
        """Route to the replica again (rolling-restart phase three). A
        restarted process has an empty radix cache — ``reset_tree``
        clears the router's view so affinity is re-learned, not
        hallucinated."""
        with self._lock:
            rep = self._replicas[name]
            rep.state = "up"
            if port is not None:
                rep.port = port
            if reset_tree:
                self._trees[name].clear()
        self._publish_health(name, True)

    def _publish_health(self, name: str, healthy: bool) -> None:
        if obs.REGISTRY.enabled:
            _REPLICA_HEALTHY.labels(replica=name).set(1 if healthy else 0)

    @property
    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    # -- routing policy ---------------------------------------------------

    def choose(self, prompt, exclude: Set[str] = frozenset(),
               now: Optional[float] = None,
               ) -> Tuple[Optional[str], str, int]:
        """Pick a replica for ``prompt``: (name, reason, predicted-match).

        Affinity wins when the best longest-prefix match is at least
        ``min_match`` tokens AND that replica's in-flight excess over
        the fleet minimum is within ``hysteresis``; otherwise
        least-loaded (round-robin among ties). Either way the chosen
        replica's tree learns the prompt. Public and HTTP-free so the
        scoring tests drive it directly.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            names = [n for n, r in self._replicas.items()
                     if r.routable and n not in exclude]
            if not names:
                return None, REASON_LEAST_LOADED, 0
            names.sort()
            if (self.tree_ttl_s is not None
                    and now - self._last_decay >= self.tree_ttl_s / 2):
                # Amortized: a full-tree sweep per routed request would
                # serialize handler threads behind O(fleet x tree) work;
                # twice per TTL keeps staleness bounded at 1.5x ttl.
                self._last_decay = now
                for n in names:
                    self._trees[n].decay(now)
            loads = {n: self._inflight[n] for n in names}
            min_load = min(loads.values())
            pick: Optional[str] = None
            reason = REASON_LEAST_LOADED
            matched = 0
            if self.affinity:
                best, best_m = None, 0
                for n in names:
                    m = self._trees[n].match(prompt)
                    if m > best_m:
                        best, best_m = n, m
                if (best is not None and best_m >= self.min_match
                        and loads[best] - min_load <= self.hysteresis):
                    pick, reason, matched = best, REASON_AFFINITY, best_m
            if pick is None:
                ties = [n for n in names if loads[n] == min_load]
                pick = ties[self._rr % len(ties)]
                self._rr += 1
            if exclude:
                reason = REASON_FAILOVER
            self._trees[pick].insert(prompt, now)
            self._inflight[pick] += 1
            self._routed[reason] += 1
            if obs.REGISTRY.enabled:
                _ROUTED.labels(replica=pick, reason=reason).inc()
                _REPLICA_INFLIGHT.labels(replica=pick).set(
                    self._inflight[pick]
                )
            return pick, reason, matched

    def finish(self, name: str, prompt, *, reason: str,
               predicted: int, hit_tokens: Optional[int]) -> None:
        """One routed stream ended. ``hit_tokens`` is the replica's own
        report (``usage.prefix_hit_tokens``; None = stream died before a
        finish event): the feedback that keeps the approximate tree
        honest — fewer hit tokens than predicted means the replica
        evicted that path, so the router forgets it too."""
        with self._lock:
            if name in self._inflight:
                self._inflight[name] = max(self._inflight[name] - 1, 0)
                if obs.REGISTRY.enabled:
                    _REPLICA_INFLIGHT.labels(replica=name).set(
                        self._inflight[name]
                    )
            if hit_tokens is None:
                return
            if reason == REASON_AFFINITY and hit_tokens > 0:
                _AFFINITY_HITS.inc()
            if hit_tokens + self.block <= predicted:
                self._trees[name].truncate(prompt, hit_tokens)

    # -- HTTP surface -----------------------------------------------------

    def handle(self, method: str, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if method == "POST" and path == "/v1/completions":
            self._completions(req)
        elif method == "GET" and path == "/router/stats":
            self.reply(req, 200, json.dumps(self.stats(), indent=2),
                       "application/json")
        elif method == "GET" and path == "/metrics":
            self.reply(req, 200, self.federated_metrics(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif method == "GET" and path == "/requests":
            self.reply(req, 200,
                       json.dumps(self.federated_requests(), indent=2),
                       "application/json")
        elif method == "GET" and path == "/healthz":
            code, body = self.federated_health()
            self.reply(req, code, json.dumps(body, indent=2),
                       "application/json")
        elif method == "GET" and path == "/flight":
            self.reply(req, 200,
                       json.dumps(self.federated_flight(), indent=2,
                                  default=str),
                       "application/json")
        elif method == "GET" and path == "/":
            self.reply(
                req, 200,
                "tree_attention_tpu serving router: "
                "POST /v1/completions  GET /router/stats  GET /metrics  "
                "GET /requests  GET /healthz  GET /flight\n",
                "text/plain",
            )
        else:
            self.reply(req, 404, f"no such endpoint: {method} {path}\n",
                       "text/plain")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "affinity": self.affinity,
                "hysteresis": self.hysteresis,
                "routed": dict(self._routed),
                "requeued": self._requeued,
                "dropped": self._dropped,
                "replicas": {
                    n: {
                        "state": r.state,
                        "port": r.port,
                        "inflight": self._inflight[n],
                        "tree_blocks": self._trees[n].blocks,
                    }
                    for n, r in sorted(self._replicas.items())
                },
            }

    def federated_metrics(self) -> str:
        """The router's registry plus every replica scrape under a
        ``replica`` label — one Prometheus target for the fleet."""
        with self._lock:
            targets = [(r.name, r.metrics_url) for r in
                       self._replicas.values() if r.metrics_url]
        # Concurrent scrapes: the targets are independent replicas, and
        # k of them being mid-restart must cost ONE timeout, not k
        # serial ones, on every Prometheus poll.
        sections: Dict[str, str] = {}
        threads = []
        for name, url in targets:

            def scrape_one(name=name, url=url):
                text = _scrape(url, timeout=2.0)
                if text is not None:
                    sections[name] = text  # per-key writes; GIL-atomic

            t = threading.Thread(target=scrape_one, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=3.0)
        own = obs.REGISTRY.to_prometheus()
        fed = federate_metrics(sections)
        return own + ("\n" + fed if fed else "")

    # -- telemetry federation (ISSUE 16) ----------------------------------

    def _obs_targets(self) -> List[Tuple[str, str, str]]:
        """(name, obs-base-url, state) per replica that exports one —
        derived from the registered ``metrics_url`` by stripping its
        ``/metrics`` path (the obs server mounts every endpoint on one
        port). In-process replicas have none: they record into THIS
        process's singletons, covered by the ``local`` section."""
        with self._lock:
            reps = list(self._replicas.values())
        out = []
        for r in reps:
            if r.metrics_url and r.metrics_url.endswith("/metrics"):
                out.append((r.name, r.metrics_url[:-len("/metrics")],
                            r.state))
        return out

    def federated_requests(self) -> Dict[str, Any]:
        """Fleet-wide request-ledger view: every entry labeled with the
        replica it ran on (``local`` = this process — where LocalReplica
        engines record)."""
        out: Dict[str, Any] = {"live": [], "recent": []}
        local = obs.REQLOG.snapshot()
        for section in ("live", "recent"):
            for entry in local[section]:
                entry["replica"] = "local"
                out[section].append(entry)
        for name, base, _state in self._obs_targets():
            snap = _get_json(f"{base}/requests", timeout=2.0)
            if not isinstance(snap, dict):
                continue
            for section in ("live", "recent"):
                for entry in snap.get(section) or []:
                    entry["replica"] = name
                    out[section].append(entry)
        return out

    def federated_health(self) -> Tuple[int, Dict[str, Any]]:
        """Fleet tick-liveness roll-up: 503 iff this process is stalled,
        any replica obs endpoint reports stalled (a WEDGED engine whose
        HTTP thread still answers — the failure /healthz exists to
        catch), or a replica the router still considers up has an
        unreachable obs endpoint (process gone mid-scrape)."""
        from tree_attention_tpu.obs.http import flight_health

        code, own = flight_health(obs.FLIGHT)
        body: Dict[str, Any] = {"router": own, "replicas": {}}
        worst = code
        for name, base, state in self._obs_targets():
            snap = _get_json(f"{base}/healthz", timeout=2.0,
                             accept_errors=True)
            if not isinstance(snap, dict):
                snap = {"status": "unreachable"}
                if state == "up":
                    worst = 503
            elif snap.get("status") == "stalled":
                worst = 503
            snap["state"] = state
            body["replicas"][name] = snap
        body["status"] = "ok" if worst == 200 else "stalled"
        return worst, body

    def federated_flight(self) -> Dict[str, Any]:
        """The router process's flight ring plus every reachable
        replica's — the fleet-wide live post-mortem."""
        out: Dict[str, Any] = {"router": obs.FLIGHT.snapshot(),
                               "replicas": {}}
        for name, base, _state in self._obs_targets():
            snap = _get_json(f"{base}/flight", timeout=2.0)
            out["replicas"][name] = (
                snap if isinstance(snap, dict) else {"error": "unreachable"}
            )
        return out

    # -- the proxy --------------------------------------------------------

    def _completions(self, req: BaseHTTPRequestHandler) -> None:
        # Validate EVERYTHING the router itself consumes BEFORE choose()
        # takes accounting (tree insert, in-flight increment, routed
        # counter): a failure after that point would leak the replica's
        # in-flight count — the ingress's brick-the-server class, one
        # tier up.
        try:
            n = int(req.headers.get("Content-Length", 0))
            body = json.loads(req.rfile.read(n) or b"{}")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int)
                               and not isinstance(t, bool)
                               for t in prompt)):
                raise ValueError(
                    "body.prompt must be a non-empty list of token ids"
                )
            if body.get("deadline_s") is not None:
                body["deadline_s"] = float(body["deadline_s"])
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self.reply(req, 400, json.dumps({"error": {
                "message": f"unroutable request: {e}",
                "type": "invalid_request"}}), "application/json")
            return
        stream = bool(body.get("stream", True))
        # Trace context (ISSUE 16): the router is the first hop that
        # traces, so it owns the trace_id — adopt the client's
        # traceparent when one arrived, mint otherwise. Each relay
        # attempt below forwards it with a fresh routing span id.
        parsed = obs.parse_traceparent(
            req.headers.get(obs.TRACEPARENT_HEADER, ""))
        trace_id = parsed[0] if parsed is not None else obs.new_trace_id()
        orig_deadline = body.get("deadline_s")
        t0 = time.monotonic()
        tried: Set[str] = set()
        relay = _ClientRelay(req, stream)
        while True:
            name, reason, predicted = self.choose(prompt, exclude=tried)
            if name is None:
                self._give_up(relay, tried)
                return
            with self._lock:
                rep = self._replicas[name]
                host, port = rep.host, rep.port
            if orig_deadline is not None:
                # The peer must see only the deadline budget actually
                # left — a failover retry does not reset the clock.
                body["deadline_s"] = max(
                    orig_deadline - (time.monotonic() - t0), 1e-3
                )
            verdict = self._relay_one(relay, name, host, port, body,
                                      prompt, reason, predicted, trace_id)
            if verdict == "done":
                return
            # "retry": the replica refused (503/shed/dead) before any
            # token reached the client — requeue on a peer.
            tried.add(name)
            with self._lock:
                self._requeued += 1

    def _give_up(self, relay: "_ClientRelay", tried: Set[str]) -> None:
        with self._lock:
            if tried:
                # Accepted work we failed to place anywhere — the count
                # the rolling-restart bench pins at zero.
                self._dropped += 1
        relay.error(
            503, "no routable replica (fleet draining or down)",
            finish_reason="shed",
        )

    def _relay_one(self, relay: "_ClientRelay", name: str, host: str,
                   port: int, body: Dict[str, Any], prompt,
                   reason: str, predicted: int, trace_id: str) -> str:
        """Proxy one attempt to one replica; returns 'done' | 'retry'."""
        import http.client

        # Routing span: a fresh span id per attempt (a failover retry is
        # its OWN hop in the trace), forwarded as the replica's parent.
        # The flow "s" point inside the slice starts the cross-process
        # arrow the replica's adopt points continue.
        rspan = obs.new_span_id()
        if obs.TRACER.active:
            with obs.span("route_relay", cat="serving",
                          args={"replica": name, "reason": reason,
                                "trace_id": trace_id,
                                "predicted_match": predicted}):
                obs.flow("s", obs.flow_id(trace_id))
        hit_tokens: Optional[int] = None
        conn = http.client.HTTPConnection(
            host, port, timeout=self.replica_timeout_s
        )
        try:
            try:
                conn.request(
                    "POST", "/v1/completions", json.dumps(body),
                    {"Content-Type": "application/json",
                     obs.TRACEPARENT_HEADER: obs.make_traceparent(
                         trace_id, rspan)},
                )
                resp = conn.getresponse()
            except OSError:
                # Connection refused/reset: the replica process is gone
                # (mid-restart). Health-wise that is DOWN until the
                # supervisor rejoins it.
                self.mark_down(name)
                return "retry"
            if resp.status != 200:
                try:
                    data = resp.read()
                except OSError:
                    self.mark_down(name)
                    data = b""
                if resp.status == 503 and not relay.started:
                    return "retry"  # draining/dead replica: requeue
                # Backpressure (429 + Retry-After) and validation (400)
                # verdicts pass through — the replica's answer IS the
                # fleet's answer.
                relay.passthrough(resp.status, data, dict(
                    (k, v) for k, v in resp.getheaders()
                    if k.lower() == "retry-after"
                ))
                return "done"
            if not relay.stream:
                try:
                    data = resp.read()
                except OSError:
                    # Replica died mid-body; nothing reached the client
                    # yet (passthrough is all-or-nothing) — requeue.
                    self.mark_down(name)
                    return "retry"
                verdict, hit_tokens = _whole_verdict(data)
                if verdict == "retry":
                    return "retry"
                relay.passthrough(200, data, {})
                return "done"
            events = _iter_events(resp)
            while True:
                try:
                    raw, payload = next(events)
                except StopIteration:
                    break
                except OSError:
                    # Replica-side READ failure mid-stream (TCP reset
                    # from a dying process, or a wedged replica that
                    # stopped sending even keepalives until the read
                    # timed out) — distinct from a client-side write
                    # failure, which raises from relay.write below and
                    # propagates (the disconnect-cancel arc).
                    self.mark_down(name)
                    if not relay.started:
                        return "retry"
                    relay.error(503, "replica lost mid-stream",
                                finish_reason="error")
                    return "done"
                if payload is None:  # comment/keepalive frame
                    relay.write(raw)
                    continue
                if payload == b"[DONE]":
                    relay.write(raw)
                    return "done"
                kind, info = _classify_event(payload)
                if kind == "token":
                    relay.write(raw, token=True)
                elif kind == "finish":
                    hit_tokens = info.get("prefix_hit_tokens")
                    if (info.get("finish_reason") == "shed"
                            and not relay.started):
                        _drain_done(resp)
                        return "retry"
                    relay.write(raw)
                else:  # replica-side error event (engine died mid-run)
                    self.mark_down(name)
                    if not relay.started:
                        _drain_done(resp)
                        return "retry"
                    relay.write(raw)
            # EOF without [DONE]: the replica vanished mid-stream.
            self.mark_down(name)
            if not relay.started:
                return "retry"
            relay.error(503, "replica lost mid-stream",
                        finish_reason="error")
            return "done"
        finally:
            conn.close()
            self.finish(name, prompt, reason=reason, predicted=predicted,
                        hit_tokens=hit_tokens)


class _ClientRelay:
    """The router->client half of one proxied request.

    Tracks whether any token bytes reached the client: before that point
    a failed attempt is silently retryable; after it, the stream is
    committed to this attempt (a replayed request would duplicate
    tokens)."""

    def __init__(self, req: BaseHTTPRequestHandler, stream: bool):
        self.req = req
        self.stream = stream
        self.started = False  # a token (or terminal body) reached the client
        self._headers_sent = False

    def _ensure_sse_headers(self) -> None:
        if not self._headers_sent:
            self.req.send_response(200)
            self.req.send_header("Content-Type", "text/event-stream")
            self.req.send_header("Cache-Control", "no-cache")
            self.req.end_headers()
            self._headers_sent = True

    def write(self, raw: bytes, token: bool = False) -> None:
        self._ensure_sse_headers()
        if token:
            self.started = True
        self.req.wfile.write(raw)
        self.req.wfile.flush()

    def passthrough(self, code: int, data: bytes,
                    headers: Dict[str, str]) -> None:
        if self._headers_sent:
            # An earlier attempt already opened the SSE stream (keepalive
            # frames only — else we would not be retrying): a status line
            # now would corrupt the protocol, so the verdict becomes an
            # SSE error frame instead.
            self.error(code, data.decode("utf-8", "replace"),
                       finish_reason="error")
            return
        self.started = True
        self.req.send_response(code)
        self.req.send_header("Content-Type", "application/json")
        self.req.send_header("Content-Length", str(len(data)))
        for k, v in headers.items():
            self.req.send_header(k, str(v))
        self.req.end_headers()
        self.req.wfile.write(data)

    def error(self, code: int, message: str, finish_reason: str) -> None:
        payload = {"error": {"message": message, "type": "server_error"},
                   "finish_reason": finish_reason}
        if self.stream and self._headers_sent:
            self.req.wfile.write(
                b"data: " + json.dumps(payload).encode() + b"\n\n"
                b"data: [DONE]\n\n"
            )
            self.req.wfile.flush()
        else:
            data = json.dumps(payload, indent=2).encode()
            self.req.send_response(code)
            self.req.send_header("Content-Type", "application/json")
            self.req.send_header("Content-Length", str(len(data)))
            self.req.end_headers()
            self.req.wfile.write(data)


# -- SSE/JSON plumbing ------------------------------------------------------


def _iter_events(resp):
    """Yield (raw_bytes, payload) per complete SSE frame: payload is the
    ``data:`` line's content, or None for comment/keepalive frames. Raw
    bytes are exactly what came off the wire — the pass-through
    guarantee lives here."""
    raw: List[bytes] = []
    payload: Optional[bytes] = None
    while True:
        line = resp.readline()
        if not line:
            return  # EOF
        raw.append(line)
        if line.startswith(b"data: "):
            payload = line[6:].strip()
        if line == b"\n":  # frame terminator
            yield b"".join(raw), payload
            raw, payload = [], None


def _classify_event(payload: bytes) -> Tuple[str, Dict[str, Any]]:
    """'token' | 'finish' | 'error' for one data: payload."""
    try:
        d = json.loads(payload)
    except json.JSONDecodeError:
        return "error", {}
    if "error" in d:
        return "error", d
    ch = (d.get("choices") or [{}])[0]
    if ch.get("finish_reason") is None:
        return "token", ch
    usage = d.get("usage") or {}
    return "finish", {
        "finish_reason": ch.get("finish_reason"),
        "prefix_hit_tokens": usage.get("prefix_hit_tokens"),
    }


def _whole_verdict(data: bytes) -> Tuple[str, Optional[int]]:
    """'retry' iff a stream:false body reports shed with no tokens."""
    try:
        d = json.loads(data)
    except json.JSONDecodeError:
        return "done", None
    ch = (d.get("choices") or [{}])[0]
    usage = d.get("usage") or {}
    if ch.get("finish_reason") == "shed" and not ch.get("token_ids"):
        return "retry", None
    return "done", usage.get("prefix_hit_tokens")


def _drain_done(resp) -> None:
    """Consume the [DONE] frame after a swallowed finish event, so the
    replica handler sees a clean read-to-end, not a reset. Best-effort:
    a replica dying right here must not abort the caller's retry."""
    try:
        for _, payload in _iter_events(resp):
            if payload == b"[DONE]":
                return
    except OSError:
        pass


def _scrape(url: str, timeout: float) -> Optional[str]:
    """Best-effort GET of one replica's /metrics text."""
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace")
    except OSError:
        return None


def _get_json(url: str, timeout: float,
              accept_errors: bool = False) -> Optional[Any]:
    """Best-effort GET + JSON parse of one replica obs endpoint.
    ``accept_errors`` keeps non-2xx BODIES (a 503 /healthz still carries
    its status JSON — that verdict is the payload, not a failure)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        if not accept_errors:
            return None
        try:
            return json.loads(e.read())
        except (OSError, ValueError):
            return None
    except (OSError, ValueError):
        return None


def federate_metrics(sections: Dict[str, str]) -> str:
    """Merge per-replica Prometheus expositions under a ``replica`` label.

    ``# HELP``/``# TYPE`` lines are kept once per metric (first replica
    wins); every sample line gains ``replica="<name>"`` as its first
    label. Pure text-to-text so the tests pin it without HTTP."""
    out: List[str] = []
    seen_meta: Set[Tuple[str, str]] = set()
    for name in sorted(sections):
        for line in sections[name].splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                # Key by (directive, metric): HELP and TYPE for one
                # metric must BOTH survive — deduping on the metric
                # name alone dropped every TYPE line behind its HELP.
                key = (parts[1] if len(parts) > 1 else "",
                       parts[2] if len(parts) > 2 else line)
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                out.append(line)
                continue
            brace = line.find("{")
            space = line.find(" ")
            if brace != -1 and (space == -1 or brace < space):
                out.append(f'{line[:brace]}{{replica="{name}",'
                           f'{line[brace + 1:]}')
            elif space != -1:
                mname, rest = line.split(None, 1)
                out.append(f'{mname}{{replica="{name}"}} {rest}')
            # else: not a Prometheus sample line (truncated scrape, an
            # error page behind the url) — drop it rather than kill the
            # fleet-wide /metrics response with an unpack error.
    return "\n".join(out) + ("\n" if out else "")
