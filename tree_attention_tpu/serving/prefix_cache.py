"""Radix prefix KV cache: shared-prompt reuse across serving requests.

Production request streams are dominated by shared prompt prefixes —
system prompts, few-shot templates, multi-turn histories — yet a plain
slot server re-runs full prefill for every admission, paying Tree-
Attention prefill compute for tokens whose KV rows already sit on the
device. RadixAttention (Zheng et al., *SGLang*, arXiv:2312.07104) showed
that a radix tree over prompt token sequences, mapping prefixes to cached
KV blocks, turns that duplicate prefill into a gather. This module is
that idea fitted to the slot engine's contracts:

- **Host-side radix tree** at ``block``-token granularity (power of two,
  bucket-friendly): each node owns ONE pool block — the KV rows of one
  ``block``-token span — keyed by that span's token tuple under its
  parent. A path from the root spells a prompt prefix; matching is a walk.
- **Device-resident block pool**: preallocated ``(P, L, Hkv, block, D)``
  K and V buffers (exact model dtype — int8 slots re-quantize on insert
  under their own frozen scales, so the pool must keep exact rows).
  Copies in and out are ONE jitted donated gather/scatter each
  (:func:`~tree_attention_tpu.models.decode.insert_prefix_blocks` /
  :func:`~tree_attention_tpu.models.decode.extract_prefix_blocks`), with
  the block-count ``nb`` padded to a small power-of-two bucket set so no
  hit or publish size ever recompiles.
- **Ref-counted LRU eviction**: a node is pinned (``refs > 0``) from the
  admission that matched or published it until that request retires;
  eviction only ever takes a refcount-0 *leaf* (evicting an interior node
  would orphan its children's prefix), least-recently-used first. The
  pool can therefore never over-commit and never frees a block a request
  still depends on — the property test in
  ``tests/test_serving_prefix.py`` hammers exactly this.

Matches are capped at ``len(prompt) - 1`` tokens (rounded down to the
block size): the suffix must keep at least one token, because sampling
the first output token needs at least one forward row. Under a mesh the
pool is **replicated** — pool blocks land at arbitrary token offsets of a
sequence-sharded cache, so no static sharding of the block axis can stay
aligned with its destination shard; replication keeps the gather local
per shard (the pool is small next to the slot cache it feeds).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tree_attention_tpu import obs
from tree_attention_tpu.models.decode import (
    KVCache,
    extract_prefix_blocks,
    insert_prefix_blocks,
)
from tree_attention_tpu.serving.block_pool import BlockAllocator
from tree_attention_tpu.models.transformer import TransformerConfig
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.prefix")

# Prefix-reuse observability (ISSUE 5). Hit/miss/reuse counters are
# host-loop truths recorded at admission; the occupancy gauge tracks the
# pool allocator. All guarded: allocation-free when the registry is off.
_HITS = obs.counter(
    "serving_prefix_hits_total",
    "admissions that matched a cached prompt prefix",
)
_MISSES = obs.counter(
    "serving_prefix_misses_total",
    "admissions that found no cached prefix (cold prefill)",
)
_TOKENS_REUSED = obs.counter(
    "serving_prefix_tokens_reused_total",
    "prompt tokens whose prefill was replaced by a pool gather",
)
_POOL_USED = obs.gauge(
    "serving_prefix_pool_blocks_used",
    "prefix pool blocks currently holding a cached KV span",
)


def _block_key(toks: List[int], j: int, block: int) -> Tuple[int, ...]:
    """The radix key of block ``j``: that span's token tuple. Callers on
    the admission hot path convert the prompt with ONE ``tolist()`` and
    slice here at C speed — per-element ``int()`` over numpy scalars
    measured slower than the device gather the paged hit replaces, which
    would have made the host the new bottleneck."""
    return tuple(toks[j * block:(j + 1) * block])


# Node tiers (ISSUE 13): a DEVICE node's ``block_id`` names a device
# pool block; a HOST node's names a row of the host tier
# (:class:`~tree_attention_tpu.serving.host_pool.HostBlockPool`) —
# demotion flips the bit down, a prefix-hit restore flips it back.
TIER_DEVICE, TIER_HOST = 0, 1


class _Node:
    """One radix node: a ``block``-token span owning one pool block
    (device tier) or one host-tier row (demoted)."""

    __slots__ = ("key", "parent", "children", "block_id", "refs",
                 "last_use", "tier")

    def __init__(self, key: Tuple[int, ...], parent: Optional["_Node"],
                 block_id: int):
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.block_id = block_id
        self.refs = 0
        self.last_use = 0
        self.tier = TIER_DEVICE


class _RadixBase:
    """The radix walk/pin/LRU machinery BOTH prefix indexes share.

    One definition of the discipline — pin-as-you-visit, LRU touch, the
    one-suffix-token match cap, refcount-0-leaf victim selection, the
    hit/miss stats vocabulary — so the gather-based :class:`PrefixCache`
    and the reference-in-place :class:`PagedPrefixIndex` can never
    silently diverge on it.
    """

    def _init_tree(self, block: int) -> None:
        if block < 1 or block & (block - 1):
            raise ValueError(f"prefix block must be a power of two, "
                             f"got {block}")
        self.block = block
        self._root = _Node((), None, -1)
        self._clock = 0
        # Run/lifetime stats (host truths; the engine snapshots + diffs
        # these per serve() run for its report).
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_use = self._clock

    def _key(self, prompt: np.ndarray, j: int) -> Tuple[int, ...]:
        return _block_key(prompt.tolist(), j, self.block)

    def _pinned_walk(self, prompt: np.ndarray) -> List[_Node]:
        """Pin + LRU-touch the longest cached path over the prompt's
        matchable blocks — capped at ``len(prompt) - 1`` tokens, because
        sampling the first output token needs at least one forward row."""
        max_blocks = (len(prompt) - 1) // self.block
        toks = prompt.tolist()  # ONE C-speed convert; see _block_key
        node = self._root
        path: List[_Node] = []
        for j in range(max_blocks):
            child = node.children.get(_block_key(toks, j, self.block))
            if child is None:
                break
            child.refs += 1
            self._touch(child)
            path.append(child)
            node = child
        return path

    def record_match(self, matched: int) -> None:
        """Count one admission's match outcome (stats + guarded
        counters). Separate from the walk so a caller that may DEFER the
        admission (the paged engine's reservation check) records only
        admissions that actually proceed."""
        if matched:
            self.hits += 1
            self.tokens_reused += matched
            if obs.REGISTRY.enabled:
                _HITS.inc()
                _TOKENS_REUSED.inc(matched)
        else:
            self.misses += 1
            if obs.REGISTRY.enabled:
                _MISSES.inc()

    def release(self, nodes: List[_Node]) -> None:
        for n in nodes:
            n.refs -= 1
            assert n.refs >= 0, "prefix node ref underflow"

    def repin(self, nodes: List[_Node]) -> List[_Node]:
        """Take one MORE pin on each node of an already-pinned path — the
        copy-on-write fork's radix arc (ISSUE 15): a forked sibling
        shares its parent's matched/published ancestor blocks, so it
        holds its own pins on the same nodes and releases them through
        its own retire, exactly like a second admission that matched the
        same path (without re-walking: the parent's pins prove the path
        is alive). Returns the nodes as the child's pinned set; the
        caller must ledger it — the ``ledger-leak`` lint pass tracks
        this acquire site."""
        for n in nodes:
            assert n.refs > 0, "repin of an unpinned prefix node"
            n.refs += 1
            self._touch(n)
        return list(nodes)

    def total_pins(self) -> int:
        """Sum of every node's refcount — the pin-balance truth. A
        drained engine (every request retired, however it exited) must
        read 0 here: admit-time pins are released at retire on EVERY
        outcome arc, cancellation and deadline expiry included (the
        chaos-harness contract, ISSUE 10)."""
        total = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            total += n.refs
        return total

    def _lru_scan(self, victim) -> Optional[_Node]:
        """The min-``last_use`` node satisfying ``victim(node)`` over the
        whole tree, or None — the ONE traversal every LRU-victim rule
        (classic leaf eviction, device-tier demotion, host-tier drop)
        parameterizes."""
        best: Optional[_Node] = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if not victim(n):
                continue
            if best is None or n.last_use < best.last_use:
                best = n
        return best

    def _lru_leaf(self) -> Optional[_Node]:
        """The least-recently-used refcount-0 leaf, or None when every
        block is pinned (directly or through a pinned descendant)."""
        return self._lru_scan(lambda n: not n.children and not n.refs)


class PrefixCache(_RadixBase):
    """Device block pool + host radix tree over prompt prefixes.

    Args:
      cfg: the served model (fixes the pool's ``(L, Hkv, D)`` and dtype).
      block: tokens per pool block (power of two; matches/publishes happen
        at this granularity).
      blocks: pool capacity ``P`` in blocks.
      mesh: replicate the pool over this mesh (see module docstring).
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        *,
        block: int = 64,
        blocks: int = 64,
        mesh: Optional[Mesh] = None,
    ):
        self._init_tree(block)
        if blocks < 1:
            raise ValueError(f"prefix pool needs >= 1 block, got {blocks}")
        self.blocks = blocks
        shape = (blocks, cfg.n_layers, cfg.n_kv_heads, block, cfg.d_head)
        if mesh is not None:
            sharding = NamedSharding(mesh, P())  # replicated (see above)
            zeros = jax.jit(
                lambda: jnp.zeros(shape, cfg.dtype), out_shardings=sharding
            )
            self.pool_k = zeros()
            self.pool_v = zeros()
        else:
            self.pool_k = jnp.zeros(shape, cfg.dtype)
            self.pool_v = jnp.zeros(shape, cfg.dtype)
        self._free: List[int] = list(range(blocks))
        self._copy = jax.jit(insert_prefix_blocks, donate_argnums=(0,))
        self._publish = jax.jit(extract_prefix_blocks, donate_argnums=(0, 1))

    # -- host radix tree --------------------------------------------------

    @property
    def blocks_used(self) -> int:
        return self.blocks - len(self._free)

    def stats(self) -> Dict[str, Any]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "tokens_reused": self.tokens_reused,
            "evictions": self.evictions,
            "pool_blocks_used": self.blocks_used,
            "pool_blocks": self.blocks,
        }

    def match(self, prompt: np.ndarray) -> Tuple[int, List[_Node]]:
        """Longest cached prefix of ``prompt`` in whole blocks, capped so
        at least one suffix token remains. Returns ``(matched_tokens,
        path)`` with every path node ref-pinned and LRU-touched — the
        caller owns the refs until it calls :meth:`release` (the serving
        engine holds them for the request's lifetime)."""
        path = self._pinned_walk(prompt)
        matched = len(path) * self.block
        self.record_match(matched)
        return matched, path

    def insert(self, prompt: np.ndarray) -> Tuple[List[_Node], List[int],
                                                  int]:
        """Ensure nodes exist for ``prompt``'s full-block prefix.

        Walks/extends the tree, allocating pool blocks (evicting LRU
        refcount-0 leaves as needed) for the missing tail; stops early —
        partial paths are valid prefixes — when the pool is fully pinned.
        Every path node is ref-pinned as it is visited, so an eviction
        triggered later in the same insert can never take an earlier path
        node. Returns ``(path, new_ids, start_block)``: the ref-held
        path, the freshly allocated pool rows still needing KV data, and
        the block index their data starts at.
        """
        nb_full = len(prompt) // self.block
        toks = prompt.tolist()
        node = self._root
        path: List[_Node] = []
        j = 0
        while j < nb_full:
            child = node.children.get(_block_key(toks, j, self.block))
            if child is None:
                break
            child.refs += 1
            self._touch(child)
            path.append(child)
            node = child
            j += 1
        start = j
        new_ids: List[int] = []
        while j < nb_full:
            bid = self._alloc()
            if bid is None:
                log.debug("prefix pool pinned full; publish stops at "
                          "block %d/%d", j, nb_full)
                break
            child = _Node(_block_key(toks, j, self.block), node, bid)
            child.refs = 1
            self._touch(child)
            node.children[child.key] = child
            path.append(child)
            new_ids.append(bid)
            node = child
            j += 1
        return path, new_ids, start

    def _alloc(self) -> Optional[int]:
        if not self._free:
            victim = self._lru_leaf()
            if victim is None:
                return None
            self._evict(victim)
        bid = self._free.pop()
        if obs.REGISTRY.enabled:
            _POOL_USED.set(self.blocks_used)
        return bid

    def _evict(self, node: _Node) -> None:
        assert not node.children and node.refs == 0
        del node.parent.children[node.key]
        self._free.append(node.block_id)
        self.evictions += 1
        if obs.REGISTRY.enabled:
            _POOL_USED.set(self.blocks_used)

    # -- device copies ----------------------------------------------------

    def _nb_bucket(self, n: int, capacity: int) -> int:
        """Power-of-two block-count bucket, capped so the copy window fits
        the cache (``nb * block <= capacity``) — the small fixed set of
        compiled gather/scatter programs. The ONE bucket rule is the
        engine's :func:`~tree_attention_tpu.serving.engine._bucket`."""
        from tree_attention_tpu.serving.engine import _bucket

        return _bucket(n, capacity // self.block, floor=1)

    def copy_into(self, cache: KVCache, slot: int, nodes: List[_Node],
                  matched: int) -> KVCache:
        """The hit path: one jitted donated gather placing ``matched``
        pooled tokens at offset 0 of ``slot`` (length set to ``matched``).
        ``cache`` must be an exact :class:`KVCache` (the batch slot cache,
        or the B=1 staging cache under int8 serving)."""
        nb = self._nb_bucket(len(nodes), cache.capacity)
        ids = np.zeros((nb,), np.int32)  # pad gathers block 0; rows masked
        ids[:len(nodes)] = [n.block_id for n in nodes]
        return self._copy(
            cache, self.pool_k, self.pool_v, jnp.asarray(ids),
            jnp.int32(matched), jnp.int32(slot),
        )

    def publish_from(self, cache: KVCache, slot: int, new_ids: List[int],
                     start_block: int) -> None:
        """The publish path: one jitted donated scatter copying the slot's
        freshly prefilled blocks ``[start_block, start_block + len(new_ids))``
        into their pool rows (padded ids point past the pool and drop)."""
        if not new_ids:
            return
        nb = self._nb_bucket(len(new_ids), cache.capacity)
        ids = np.full((nb,), self.blocks, np.int32)  # OOB pad -> dropped
        ids[:len(new_ids)] = new_ids
        self.pool_k, self.pool_v = self._publish(
            self.pool_k, self.pool_v, cache.k, cache.v,
            jnp.int32(slot), jnp.asarray(ids), jnp.int32(start_block),
        )


class PagedPrefixIndex(_RadixBase):
    """Radix prefix index over the UNIFIED paged pool — reference in place.

    The paged mirror of :class:`PrefixCache`: the same host radix tree at
    ``block``-token granularity, the same pin/LRU-leaf discipline, but
    nodes reference blocks of the ONE pool every slot already reads
    through its block table (:class:`~tree_attention_tpu.models.decode
    .PagedKVCache`), so both halves of prefix reuse move ZERO device
    bytes:

    - a **hit** pins the matched path and hands the engine its block ids;
      the engine writes them into the slot's table row — a host-side
      integer update where the contiguous path paid a pool→slot gather;
    - a **publish** ADOPTS the prefilling slot's private blocks
      (:meth:`adopt`): ownership moves to the tree via the allocator's
      ledger, the KV bytes stay exactly where the prefill wrote them.

    ``max_cached`` bounds how many blocks the tree may retain (the
    deprecated ``prefix_pool_blocks`` view of the world — useful for
    tests and for bounding cold-cache memory); ``None`` lets retention
    grow to whatever the pool's eviction pressure allows. The index
    registers itself as the allocator's evictor, so slot allocations
    under a full free list recycle LRU refcount-0 leaves automatically.

    **Sequence-sharded pools (ISSUE 18)** need no changes here: radix
    keys are host-side token tuples and node payloads are GLOBAL block
    ids — which mesh shard physically holds a block's pool row is an
    allocator detail (``ShardedBlockAllocator.shard_of``), invisible to
    matching, pinning, adoption, and eviction. A hit under
    ``kv_shard="seq"`` is the same host-side table update; the decode
    merge finds the reused rows wherever they live.
    """

    def __init__(self, *, block: int, alloc: "BlockAllocator",
                 max_cached: Optional[int] = None,
                 host_pool: Optional[Any] = None):
        self._init_tree(block)
        self.alloc = alloc
        self.max_cached = max_cached
        self._cached = 0  # DEVICE blocks the tree currently owns
        self._host_cached = 0  # demoted nodes (host-tier rows)
        # KV tiering (ISSUE 13): with a host pool attached, eviction
        # DEMOTES the LRU victim's block into it (the node survives with
        # its tier bit flipped) instead of freeing, and a later match on
        # the demoted path restores it — see host_pool.py's module
        # docstring for the block's full journey.
        self.host = host_pool
        alloc.set_evictor(self.evict_one, self.evictable_blocks)

    # -- stats (same vocabulary as PrefixCache; the engine snapshots) -----

    @property
    def blocks_used(self) -> int:
        return self._cached

    def stats(self) -> Dict[str, Any]:
        out = {
            "hits": self.hits,
            "misses": self.misses,
            "tokens_reused": self.tokens_reused,
            "evictions": self.evictions,
            "pool_blocks_used": self._cached,
            "pool_blocks": (self.max_cached if self.max_cached is not None
                            else self.alloc.blocks),
        }
        if self.host is not None:
            out.update(self.host.stats())
        return out

    # -- match / pin (identical contract to PrefixCache.match) ------------

    def match(self, prompt: np.ndarray,
              record: bool = True) -> Tuple[int, List[_Node]]:
        """Longest cached prefix in whole blocks (capped so one suffix
        token remains), path ref-pinned and LRU-touched; the caller holds
        the pins admit→retire and reads KV through ``node.block_id`` —
        no copy, no staging, zero device bytes. ``record=False`` defers
        the hit/miss stats to :meth:`record_match`: the engine matches
        BEFORE it knows whether the admission's block reservation fits,
        and a deferred admission re-matches later (double-counting the
        monotonic counters would corrupt the reuse accounting)."""
        path = self._pinned_walk(prompt)
        matched = len(path) * self.block
        if record:
            self.record_match(matched)
        return matched, path

    # -- publish by adoption ----------------------------------------------

    def adopt(self, prompt: np.ndarray, phys: Dict[int, int],
              held: List[_Node]) -> Tuple[List[_Node], List[int]]:
        """Publish a completed prompt by HANDING OVER the slot's blocks.

        ``phys`` maps the prompt's logical block index ``j`` to the
        physical pool block the slot privately owns there; ``held`` is
        the request's admit-pinned matched path (its pins CARRY OVER —
        adopt neither re-pins nor releases them). Walking past the held
        prefix: a missing node adopts ``phys[j]`` (ownership moves to
        the tree, refs=1 held by this request until retire); a node
        another request published since our admit is walked THROUGH with
        only a call-scoped guard pin — the slot keeps reading its own
        private copy (identical bytes, freed at retire), and a
        PERSISTENT pin on a refcount-0 node here could convert a block
        some admission's reservation is backed by from evictable to
        pinned, stranding that reservation (the allocator's one
        soundness invariant). The guard pin exists because the budget
        eviction below picks LRU refcount-0 LEAVES — without it, the
        very leaf the walk is standing on could be evicted mid-adopt,
        and the new child would attach under a detached parent (an
        orphaned subtree whose block leaks). Dropped before returning,
        so availability accounting is untouched. Adoption stops early
        when the retention budget is pinned full — partial paths are
        valid prefixes, exactly like PrefixCache's pinned-pool publish
        stop. Returns ``(path, adopted_logical)``: the pinned nodes
        this request now holds (held + created) and which logical
        blocks changed owner.
        """
        nb_full = len(prompt) // self.block
        toks = prompt.tolist()
        node = held[-1] if held else self._root
        path: List[_Node] = list(held)
        adopted: List[int] = []
        guard: List[_Node] = []  # call-scoped pins on walked-through nodes
        for j in range(len(held), nb_full):
            key = _block_key(toks, j, self.block)
            child = node.children.get(key)
            if child is None:
                bid = phys.get(j)
                if bid is None:
                    break  # the slot holds no private block here
                if self.max_cached is not None \
                        and self._cached >= self.max_cached:
                    if not self.evict_one():
                        log.debug("prefix index pinned full; publish "
                                  "stops at block %d/%d", j, nb_full)
                        break
                child = _Node(key, node, bid)
                child.refs = 1
                self.alloc.publish(bid)
                self._cached += 1
                adopted.append(j)
                node.children[key] = child
                path.append(child)
            else:
                child.refs += 1
                guard.append(child)
            self._touch(child)
            node = child
        self.release(guard)
        if obs.REGISTRY.enabled:
            _POOL_USED.set(self._cached)
        return path, adopted

    # -- eviction / demotion (the allocator's hook) -----------------------

    def _lru_device_victim(self) -> Optional[_Node]:
        """The LRU refcount-0 DEVICE-tier node with no device-tier
        children, or None when every device block is pinned. Without a
        host tier this is exactly the classic refcount-0 leaf (host
        nodes never exist); with one, a device node whose children were
        all demoted already is a valid victim — demoting it keeps the
        node (and its host subtree's prefix) intact."""
        return self._lru_scan(
            lambda n: n.tier == TIER_DEVICE and not n.refs
            and not any(c.tier == TIER_DEVICE
                        for c in n.children.values())
        )

    def _drop_host_lru(self) -> bool:
        """The host tier's own LRU eviction: delete the least-recently-
        used refcount-0 host-tier LEAF from the tree (the ``dropped``
        arc — same leaf-only discipline as device eviction, so no
        prefix is ever orphaned). A still-pending demotion's device
        block frees directly: its copy never ran and never will."""
        best = self._lru_scan(
            lambda n: n.tier == TIER_HOST and not n.refs
            and not n.children
        )
        if best is None:
            return False
        del best.parent.children[best.key]
        bid = self.host.drop(best.block_id)
        if bid is not None:
            self.alloc.free_demoted(bid)
        self._host_cached -= 1
        return True

    def evict_one(self) -> bool:
        """Recycle one LRU refcount-0 device victim: DEMOTE it into the
        host tier when one is attached (the node survives — a later
        match restores it), plain-evict otherwise (or when the host tier
        is pinned full even after dropping its own LRU). False when
        every device block is pinned."""
        victim = self._lru_device_victim()
        if victim is None:
            return False
        if self.host is not None:
            row = self.host.alloc()
            while row is None and self._drop_host_lru():
                row = self.host.alloc()
            if row is not None:
                self.alloc.demote_cached(victim.block_id)
                self.host.enqueue(row, victim.block_id)
                victim.tier = TIER_HOST
                victim.block_id = row
                self._cached -= 1
                self._host_cached += 1
                self.evictions += 1
                if obs.REGISTRY.enabled:
                    _POOL_USED.set(self._cached)
                return True
            log.debug("host tier pinned full; falling back to eviction")
        # Classic eviction: the prefix is forgotten. A demoted-tier
        # victim never reaches here (victims are device-tier), so the
        # only children it could orphan are host nodes — and a device
        # victim with host children only falls through when the host
        # tier could not take it, in which case its host subtree must
        # drop with it (leaf-first, so it is already empty: _drop_host_lru
        # failing means every host leaf is pinned, which pins this path).
        if victim.children:
            return False
        del victim.parent.children[victim.key]
        self.alloc.free_cached(victim.block_id)
        self._cached -= 1
        self.evictions += 1
        if obs.REGISTRY.enabled:
            _POOL_USED.set(self._cached)
        return True

    def evictable_blocks(self) -> int:
        """DEVICE blocks in fully-unpinned subtrees — exactly what
        repeated :meth:`evict_one` calls can reach (device-leaf-first
        eviction drains an unpinned subtree's device blocks completely;
        a pinned descendant protects every ancestor on its path).
        Host-tier nodes hold no device block and count 0."""

        def walk(node: _Node) -> Tuple[bool, int, int]:
            has_pin = node.refs > 0
            dev_blocks = 1 if node.tier == TIER_DEVICE else 0
            kid_evictable = 0
            for c in node.children.values():
                p, b, e = walk(c)
                has_pin |= p
                dev_blocks += b
                kid_evictable += e
            if has_pin:
                return True, dev_blocks, kid_evictable
            return False, dev_blocks, dev_blocks

        return sum(walk(c)[2] for c in self._root.children.values())

    # -- restore (the engine's hit path, ISSUE 13) ------------------------

    def demoted_in(self, nodes: List[_Node]) -> List[_Node]:
        """The host-tier nodes of a matched (pinned) path, path order."""
        return [n for n in nodes if n.tier == TIER_HOST]

    def restore_nodes(
        self, nodes: List[_Node], alloc_device: Any
    ) -> Tuple[List[int], List[int]]:
        """Bring a pinned path's demoted nodes back to the device tier.

        Two arcs per node: a still-PENDING demotion cancels (the device
        bytes never left — the block hands straight back to the tree,
        zero copies, zero allocations); a flushed one takes a fresh
        device block from ``alloc_device()`` (the admission's
        reservation backs it) and joins the batched H2D scatter the
        caller dispatches. Returns ``(host_rows, new_bids)`` — equal-
        length lists of the rows to copy and their destination blocks;
        the caller reads the rows (:meth:`HostBlockPool.read`), scatters,
        then releases them. Tier bits and ownership flip here, so the
        tree's view is consistent the moment this returns."""
        rows: List[int] = []
        bids: List[int] = []
        for n in nodes:
            assert n.tier == TIER_HOST and n.refs > 0, \
                "restore of an unpinned or device-tier node"
            row = n.block_id
            bid = self.host.cancel_pending(row)
            if bid is not None:
                self.alloc.undemote(bid)
            else:
                bid = alloc_device()
                self.alloc.publish(bid)  # private -> tree-owned
                rows.append(row)
                bids.append(bid)
            n.block_id = bid
            n.tier = TIER_DEVICE
            self._cached += 1
            self._host_cached -= 1
        if obs.REGISTRY.enabled:
            _POOL_USED.set(self._cached)
        return rows, bids
