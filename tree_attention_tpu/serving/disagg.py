"""Disaggregated prefill/decode serving: split-phase engine pools with a
zero-copy KV handoff (ISSUE 12).

Prefill is compute-bound and bursty; decode is memory-bound and steady.
Fusing them in one engine is why the Sarathi chunk budget exists at all —
and even chunked admission puts ``Tq > 1`` rows into decode ticks during
admission storms, so decode inter-token latency (TBT) p99 degrades with
prefill load. DistServe (arXiv:2401.09670) and Splitwise (arXiv:2311.18677)
split the two phases onto separate pools, removing that interference class
entirely. This module is the in-process shape of that split:

- a **prefill worker**: a :class:`~tree_attention_tpu.serving.engine
  .SlotServer` that runs admission + chunked prefill ONLY — its slots go
  ``free -> prefill -> await -> handoff``, never ``live``, and its ticks
  never carry a decode row;
- a **decode worker**: a second ``SlotServer`` whose ticks are pure
  ``Tq=1`` decode (or speculative-verify) programs — no admission, no
  chunks; its slots are fed exclusively by adoption from the handoff
  queue;
- **one shared block pool**: both workers are constructed over a single
  :class:`~tree_attention_tpu.serving.block_pool.BlockAllocator` (and one
  :class:`~tree_attention_tpu.serving.prefix_cache.PagedPrefixIndex` when
  the radix cache is on), and :class:`DisaggServer` rebinds both caches
  to ONE set of device pool arrays, relaying the (functionally updated)
  pool between the workers after every dispatch. A handoff therefore
  moves **zero KV bytes**: the finished prefill's blocks change owner in
  the allocator-audited ledger (:meth:`BlockAllocator.transfer_private`),
  the decode worker writes the same physical ids into its own table row,
  and the unspent worst-case reservation moves with the request — it is
  *transferred*, not re-reserved, so admission soundness holds across the
  handoff with no window in which a third request could steal the blocks.
  (Under int8 the per-BLOCK scale scalars — ISSUE 13 — are POOL state and
  relay with the KV arrays; the handoff itself moves no scale metadata.)

**The handoff queue is the prefill slot itself.** A request whose final
chunk completed parks in its prefill slot in state ``handoff`` until a
decode slot frees up; adoption then transfers every resource in one host
step. This buys two things: natural backpressure (a saturated decode pool
stalls prefill admissions instead of growing an unbounded queue), and the
one-retire-path contract — cancel/deadline while *queued for handoff* is
just :meth:`SlotServer._retire` on the prefill worker, the same code path
as every other exit arc, releasing blocks, pins, and reservations exactly
once on whichever worker owns the request at that moment.

**CPU-proxy caveat (honest accounting).** In-process, both workers run on
ONE device and the tick loop serializes them, so a wall-clock decode gap
would absorb the prefill worker's tick time — noise a two-device
deployment does not pay. The loop therefore *attributes* time per worker:
after each prefill tick, every live decode slot's last-token clock is
shifted forward by the prefill section's wall time, so recorded TBT is
the decode worker's own cost — what a dedicated decode device would
serve. The serialized totals are still reported
(``ServeReport.handoff["prefill_tick_s"/"decode_tick_s"]``) so nothing
hides; absolute seconds are CPU-proxy numbers either way, the structure
(decode ticks never widen with prefill load) is what transfers.

Threading contract: like ``SlotServer``, the ONLY thread-safe seams are
:meth:`cancel` and :meth:`request_drain` (mailboxes under ``self._lock``,
an RLock, swept at tick start) plus a live ``RequestSource``'s submit
side; everything else — both engines' state, the handoff queue, the
shared allocator — is touched only by the serve-loop thread.
``DisaggServer`` exposes the same ``serve``/``cancel``/``request_drain``/
``slots``/``slo``/``leak_report`` surface as ``SlotServer``, so the HTTP
ingress, the fleet supervisor, and the chaos harness stack on top
unchanged (the CLI's ``--serve-disagg``, composable with
``--serve-http``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tree_attention_tpu import obs
from tree_attention_tpu.obs.flight import FLIGHT
from tree_attention_tpu.models.transformer import Params, TransformerConfig
from tree_attention_tpu.serving.block_pool import (
    BlockAllocator,
    ShardedBlockAllocator,
)
from tree_attention_tpu.serving.engine import (
    OUTCOME_BUDGET,
    OUTCOME_CANCELLED,
    OUTCOME_DEADLINE,
    OUTCOME_EOS,
    OUTCOME_ERROR,
    OUTCOME_SHED,
    Request,
    RequestSource,
    ServeReport,
    SlotServer,
    StaticRequestSource,
    _SLOTS_OCCUPIED,
    _TBT,
    _TOKENS,
    _TTFT,
)
from tree_attention_tpu.serving.speculation import Drafter, PackedSpec
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.disagg")

# Handoff observability (ISSUE 12): counts are host-loop truths recorded
# at the adoption step; the queue gauge tracks prefill slots parked in
# state "handoff". All guarded: allocation-free when the registry is off.
_HANDOFFS = obs.counter(
    "serving_handoff_total",
    "requests handed off prefill->decode (pure ownership transfer, "
    "zero KV bytes moved in-process)",
)
_HANDOFF_QUEUE = obs.gauge(
    "serving_handoff_queue",
    "requests parked in prefill slots awaiting decode-pool adoption",
)


class DisaggServer:
    """Two ``SlotServer`` workers over one block pool, one tick loop.

    Args (the shared ones mean exactly what they mean on
    :class:`SlotServer`; both workers are built from the same params/cfg):

      prefill_slots: batch size of the prefill worker — how many prompts
        may be in (chunked) prefill or parked for handoff at once.
      decode_slots: batch size of the decode worker — the max concurrent
        decoding requests (the fused engine's ``slots`` analog for
        steady-state concurrency).
      kv_blocks: TOTAL shared pool capacity in blocks (both workers and
        the prefix tree draw from it). Default:
        ``(prefill_slots + decode_slots) * ceil(cache_len / kv_block)``
        — the fused engine's default at equal total slots, so fused vs
        disaggregated comparisons are equal-bytes by construction.
      speculate / draft_k / drafter: speculative decoding on the DECODE
        pool (the prefill worker never speculates — it has nothing to
        draft against).
      prefix_cache: shared radix reuse across the pair — the prefill
        worker matches/adopts against ONE :class:`PagedPrefixIndex`, the
        decode worker inherits each request's pins at handoff and
        releases them at retire. int8 serving shares too (ISSUE 13):
        blocks carry per-BLOCK scales in the pool, so a published int8
        block is self-contained on either worker.
      host_blocks: KV tiering across the pair (ISSUE 13) — capacity of
        the host-RAM demotion tier under the SHARED pool (0 = off).
        The tier belongs to the shared radix tree: the prefill worker
        (the matching side) runs the restores and the staged demotion
        flushes; the relayed pool arrays keep both workers' views of a
        restored block identical. Requires ``prefix_cache=True``.
    """

    def __init__(
        self,
        params: Params,
        cfg: TransformerConfig,
        *,
        prefill_slots: int,
        decode_slots: int,
        cache_len: int,
        mesh: Optional[Mesh] = None,
        quantize: bool = False,
        quant_kernel: str = "q8q",
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        prefill_chunk: int = 256,
        prefill_budget: Optional[int] = None,
        slo_ttft: float = 1.0,
        slo_tbt: float = 0.2,
        slo_window: int = 1024,
        prefix_cache: bool = False,
        prefix_block: int = 64,
        prefix_pool_blocks: Optional[int] = None,
        kv_block: Optional[int] = None,
        kv_blocks: Optional[int] = None,
        speculate: bool = False,
        draft_k: int = 4,
        drafter: Union[str, Drafter, None] = None,
        host_blocks: int = 0,
        kv_shard: str = "replicated",
    ):
        if prefill_slots < 1 or decode_slots < 1:
            raise ValueError(
                f"disaggregation needs >= 1 slot per pool, got "
                f"prefill_slots={prefill_slots} decode_slots={decode_slots}"
            )
        if kv_block is None:
            kv_block = prefix_block if prefix_cache else 64
        self.prefill_slots = prefill_slots
        self.decode_slots = decode_slots
        self.slots = prefill_slots + decode_slots  # the ingress contract
        self.cache_len = cache_len
        self.cfg = cfg
        self.params = params
        self.quantize = quantize
        self.kv_layout = "paged"
        self.kv_block = kv_block
        npb = -(-cache_len // kv_block)
        self.kv_blocks = (
            self.slots * npb if kv_blocks is None else kv_blocks
        )
        # ONE ledger for both workers: every reservation, allocation, and
        # ownership transition — including the handoff's transfer — runs
        # through this allocator, so the soundness audit covers the pair.
        # Under kv_shard="seq" (ISSUE 18) the ledger is the sharded
        # variant — the handoff still moves zero KV bytes because block
        # ownership is a host-side notion regardless of which mesh shard
        # physically holds a block's pool row.
        if kv_shard not in ("replicated", "seq"):
            raise ValueError(
                f"kv_shard must be 'replicated' or 'seq', got {kv_shard!r}"
            )
        self.kv_shard = kv_shard
        if kv_shard == "seq":
            from tree_attention_tpu.parallel.mesh import AXIS_SEQ

            w = max(mesh.shape.get(AXIS_SEQ, 1), 1) if mesh is not None else 1
            self.kv_blocks = -(-self.kv_blocks // w) * w
            self.pool = ShardedBlockAllocator(self.kv_blocks, w)
        else:
            self.pool = BlockAllocator(self.kv_blocks)
        if host_blocks < 0:
            raise ValueError(f"host_blocks must be >= 0, got {host_blocks}")
        if host_blocks and not prefix_cache:
            raise ValueError(
                "host_blocks KV tiering requires prefix_cache=True "
                "(demotion is what radix eviction becomes; with no "
                "radix tree nothing ever demotes)"
            )
        self.host_blocks = host_blocks
        self.host_pool = None
        if host_blocks:
            from tree_attention_tpu.serving.host_pool import HostBlockPool

            self.host_pool = HostBlockPool(
                host_blocks,
                n_layers=cfg.n_layers,
                n_kv_heads=cfg.n_kv_heads,
                block=kv_block,
                d_head=cfg.d_head,
                dtype=np.int8 if quantize else np.dtype(
                    jnp.dtype(cfg.dtype).name),
                quantized=quantize,
            )
        self.prefix_index = None
        if prefix_cache:
            from tree_attention_tpu.serving.prefix_cache import (
                PagedPrefixIndex,
            )

            self.prefix_index = PagedPrefixIndex(
                block=kv_block, alloc=self.pool,
                max_cached=prefix_pool_blocks,
                host_pool=self.host_pool,
            )
        common = dict(
            cache_len=cache_len, mesh=mesh, quantize=quantize,
            quant_kernel=quant_kernel, temperature=temperature,
            top_k=top_k,
            admission="chunked", slo_ttft=slo_ttft, slo_tbt=slo_tbt,
            slo_window=slo_window, kv_layout="paged", kv_block=kv_block,
            kv_shard=kv_shard,
            block_pool=self.pool, prefix_index=self.prefix_index,
        )
        self.prefill = SlotServer(
            params, cfg, slots=prefill_slots, seed=seed,
            prefill_chunk=prefill_chunk, prefill_budget=prefill_budget,
            **common,
        )
        self.decode = SlotServer(
            params, cfg, slots=decode_slots, seed=seed + 1,
            prefill_chunk=prefill_chunk,
            speculate=speculate, draft_k=draft_k, drafter=drafter,
            **common,
        )
        # ONE SLO monitor for the pair: TTFT is observed on the prefill
        # worker, TBT on the decode worker, retires on whichever worker
        # owns the request — a split monitor would halve every window.
        self.slo = self.prefill.slo
        self.decode.slo = self.slo
        # ONE set of device pool arrays: the decode worker's freshly
        # allocated (all-zero, identical) pools are dropped in favor of
        # the prefill worker's, and every dispatch below relays the
        # updated arrays to the other worker — the rebinding that makes
        # "zero KV bytes moved" literal rather than aspirational.
        self.decode.cache = dataclasses.replace(
            self.decode.cache, k=self.prefill.cache.k,
            v=self.prefill.cache.v,
        )
        if quantize:
            # Per-BLOCK scales are POOL state (ISSUE 13), shared exactly
            # like the KV pools: drop the decode worker's fresh scale
            # arrays for the prefill worker's, and the per-dispatch
            # relay below carries them — the handoff itself moves no
            # scale metadata at all (it used to copy the per-slot frozen
            # rows; per-block scales travel with their blocks for free).
            self.decode.cache = dataclasses.replace(
                self.decode.cache,
                k_scale=self.prefill.cache.k_scale,
                v_scale=self.prefill.cache.v_scale,
            )
        if self.host_pool is not None:
            # KV tiering across the pair (ISSUE 13): the tier belongs to
            # the SHARED tree, so the workers were built with
            # host_blocks=0 and the pair wires the prefill worker — the
            # matching side, where restores happen — as the tier's
            # engine: its _paged_hit restores demoted paths, its
            # _flush_demotions runs the staged D2H batches (registered
            # as the shared allocator's flusher so a dry reservation on
            # EITHER worker can force one; the relayed pool arrays make
            # prefill.cache the live pool whichever worker dispatched
            # last). The loop relays after restores and flushes at end
            # of tick, mirroring SlotServer.serve.
            self.prefill.attach_host_tier(self.host_pool)
        # Fork families (ISSUE 15) need sibling slots on the SAME engine
        # as the parent's prefill — which disaggregation splits across
        # the handoff — so n/best_of > 1 requests are rejected at
        # validation; mid-generation fork(uid) still works, applied on
        # the decode worker (live slots only exist there).
        self.prefill._fork_ok = False
        # Token-tree sibling decode (ISSUE 20) is a fused-engine feature:
        # the decode worker's tick loop serves chain verify rows only, so
        # a mid-generation fork must take the sibling-slot path, never the
        # in-slot tree conversion.
        self.decode._tree_sampling = False
        # Thread-safe control mailboxes — the ingress's seams. RLock: the
        # drain flag is flipped from SIGTERM handlers (the ingress's
        # install_drain_signals contract), which may interrupt a handler
        # thread already holding the lock.
        self._lock = threading.RLock()
        self._cancel_uids: set = set()
        self._draining = False
        self._fork_uids: List[int] = []
        # Lifetime handoff stats (public, loop-thread only; serve() diffs
        # them per run for ServeReport.handoff).
        self.handoffs = 0

    # -- ingress-facing control (thread-safe) ------------------------------

    def cancel(self, uid: int) -> None:
        """Cancel request ``uid`` (any thread). Applied at the next tick
        sweep on whichever worker owns it — queued, prefilling, parked
        for handoff, or decoding; unknown uids are a no-op."""
        with self._lock:
            self._cancel_uids.add(uid)

    def fork(self, uid: int) -> None:
        """Branch live request ``uid`` mid-generation (any thread,
        ISSUE 15) — applied by the control sweep on the DECODE worker,
        where live slots exist; the branch shares the request's full
        ancestor blocks in the pair's ONE pool and retires through the
        decode worker's one retire path."""
        with self._lock:
            self._fork_uids.append(uid)

    def _take_forks(self) -> List[int]:
        with self._lock:
            out = self._fork_uids
            self._fork_uids = []
            return out

    @property
    def _fork_carry(self) -> Dict[int, int]:
        """The decode worker's deferred-fork carry (the sweep's retry
        state lives where the forks apply)."""
        return self.decode._fork_carry

    def _apply_forks(self, forks: List[int], tick: int, pending) -> None:
        """Mirror of the fused engine's fork arc, applied on the decode
        worker. A request still QUEUED, prefilling, or parked for
        handoff lives on the prefill side — the decode worker cannot
        see it, so those uids ride the retry carry (exactly the fused
        engine's not-yet-live race) instead of aging out as unknown.
        A fork's tail-block copy donates the SHARED pool arrays, so a
        sweep that forked anything relays them to the prefill worker
        before its next dispatch."""
        upstream = list(pending) + [
            rq for rq in self.prefill._slot_req if rq is not None
        ]
        forked0 = self.decode._forks_life
        self.decode._apply_forks(forks, tick, upstream)
        if self.decode._forks_life != forked0:
            self._relay_pool(self.decode, self.prefill)

    def request_drain(self) -> None:
        """Begin graceful drain (any thread): stop admitting, shed the
        queue, finish everything in flight — handoffs included — then
        return from :meth:`serve`."""
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    @property
    def all_slots_free(self) -> bool:
        return self.prefill.all_slots_free and self.decode.all_slots_free

    def _take_control(self) -> Tuple[set, bool]:
        with self._lock:
            cancels = self._cancel_uids
            self._cancel_uids = set()
            return cancels, self._draining

    def prefix_stats(self) -> Dict[str, Any]:
        return ({} if self.prefix_index is None
                else dict(self.prefix_index.stats()))

    def leak_report(self) -> Dict[str, int]:
        """The pair's no-leak invariant: after a drained run the shared
        pool must hold no slot-private blocks ON EITHER WORKER, no
        unspent reservations, and no pinned radix nodes — a handoff that
        dropped or double-counted a block shows up here."""
        out = {
            "blocks_private": (
                sum(len(s) for s in self.prefill._slot_private)
                + sum(len(s) for s in self.decode._slot_private)
            ),
            "blocks_used": self.pool.used,
            "blocks_reserved": self.pool.reserved,
            "blocks_shared": self.pool.shared_count,
            "blocks_cached": 0,
            "pins": 0,
        }
        if self.prefix_index is not None:
            out["blocks_cached"] = self.prefix_index.blocks_used
            out["pins"] = self.prefix_index.total_pins()
        if self.host_pool is not None:
            # Host-tier occupancy is legitimate retained cache (like
            # blocks_cached), surfaced for the harness's accounting.
            out["host_blocks_used"] = self.host_pool.used
        return out

    def slots_snapshot(self) -> List[Dict[str, Any]]:
        """The pair's ``/slots`` view (ISSUE 16): both workers' rows,
        labeled — a parked handoff shows as prefill state ``handoff``
        until a decode row adopts it."""
        out: List[Dict[str, Any]] = []
        for worker, eng in (("prefill", self.prefill),
                            ("decode", self.decode)):
            for row in eng.slots_snapshot():
                row["worker"] = worker
                out.append(row)
        return out

    # -- the zero-copy handoff ---------------------------------------------

    def _relay_pool(self, src: SlotServer, dst: SlotServer) -> None:
        """Rebind ``dst``'s cache to ``src``'s just-produced pool arrays.

        Every dispatch donates its cache, so after a worker steps, the
        OTHER worker's cache still references the pre-step (possibly
        consumed) pool buffers; this host-side pointer swap — no device
        work — restores the single-pool invariant before the next
        dispatch. Tables and lengths are per-worker and untouched; the
        per-BLOCK scale arrays (ISSUE 13) are POOL state like the KV
        arrays and relay with them under int8."""
        new = dict(k=src.cache.k, v=src.cache.v)
        if self.quantize:
            new.update(k_scale=src.cache.k_scale,
                       v_scale=src.cache.v_scale)
        dst.cache = dataclasses.replace(dst.cache, **new)

    def _adopt(self, p: int, d: int, tick: int,
               pending_reset: Dict[int, int]) -> None:
        """Move one parked request from prefill slot ``p`` to decode slot
        ``d`` — the handoff proper. Pure ownership transfer: the
        allocator audits that every transferred block is privately owned
        (:meth:`BlockAllocator.transfer_private`), the table row / private
        set / unspent reservation / radix pins / sampling state move to
        the decode worker's ledgers, and the prefill slot is scrubbed
        WITHOUT freeing anything — the request now retires (on any arc)
        through the decode worker's one retire path."""
        pf, dc = self.prefill, self.decode
        req = pf._slot_req[p]
        plen = len(req.prompt)
        bids = pf._slot_private[p]
        nb = pf._slot_nblocks[p]
        self.pool.transfer_private(bids)
        dc._host_table[d, :nb] = pf._host_table[p, :nb]
        dc._host_table[d, nb:] = 0
        dc._slot_nblocks[d] = nb
        dc._slot_private[d] = bids
        dc._slot_reserve[d] = pf._slot_reserve[p]
        dc._table_dirty = True
        # The request's pinned radix path (admit-time hit + published
        # blocks) — the pins carry over and release at decode retire.
        dc._slot_nodes[d] = pf._slot_nodes[p]
        dc._slot_req[d] = req
        dc._slot_tokens[d] = pf._slot_tokens[p]  # [first token]
        dc._slot_admit[d] = pf._slot_admit[p]
        dc._slot_wait[d] = pf._slot_wait[p]
        dc._slot_ttft[d] = pf._slot_ttft[p]
        dc._slot_max_tbt[d] = pf._slot_max_tbt[p]
        dc._slot_prefix_hit[d] = pf._slot_prefix_hit[p]
        dc._prompt_np[d] = pf._prompt_np[p]
        dc._last_tok_t[d] = pf._last_tok_t[p]
        # Sampling state moves with the request (ISSUE 15): the PRNG key
        # row (reproducibility is fold_in(key, stream-index) — the
        # handoff must not re-derive from the decode worker's base),
        # per-slot temperature/top-k, the branch index, and the running
        # cumulative logprob.
        dc._keys = dc._keys.at[d].set(pf._keys[p])
        dc._temp_np[d] = pf._temp_np[p]
        dc._topk_np[d] = pf._topk_np[p]
        dc._slot_index[d] = pf._slot_index[p]
        dc._slot_cum_lp[d] = pf._slot_cum_lp[p]
        dc._slot_shared[d] = set()
        dc._slot_clen[d] = plen  # committed rows = the prompt; the first
        # token is the pending tip (the spec rollback ledger starts here)
        first = dc._slot_tokens[d][-1]
        # _tok_host may be a read-only view of the device fetch — copy
        # before installing the adopted slot's parked token ((S,) int32).
        th = np.array(dc._tok_host)
        th[d] = first
        dc._tok_host = th
        if dc._speculate:
            dc._hist_buf[d, :plen] = dc._prompt_np[d]
            dc._hist_buf[d, plen] = first
            dc._hist_len[d] = plen + 1
        dc._slot_state[d] = "live"
        # The request's admit->retire span follows the request.
        dc._slot_span[d] = pf._slot_span[p]
        # The decode worker's device cache still carries a STALE length
        # for slot d (its prefill happened in the other worker's length
        # vector) — the slot's first decode dispatch resets it to plen.
        pending_reset[d] = plen
        # Scrub the prefill slot WITHOUT releasing resources — they just
        # changed owner. No allocator generation bump either: nothing
        # became available, so a deferred admission must keep waiting.
        pf._slot_req[p] = None
        pf._slot_tokens[p] = []
        pf._slot_state[p] = "free"
        pf._prompt_np[p] = None
        pf._slot_nodes[p] = []
        pf._slot_private[p] = set()
        pf._slot_reserve[p] = 0
        pf._host_table[p, :] = 0
        pf._slot_nblocks[p] = 0
        pf._table_dirty = True
        pf._slot_span[p] = None
        self.handoffs += 1
        if obs.REGISTRY.enabled:
            _HANDOFFS.inc()
        if obs.TRACER.active:
            obs.instant("handoff", cat="serving", args={
                "rid": req.uid, "tick": tick, "from_slot": p,
                "to_slot": d, "blocks": nb, "kv_bytes_moved": 0,
            })
            if req.trace is not None:
                # Step point of the request's cross-process flow at the
                # prefill→decode adoption (ISSUE 16): the trace context
                # rides the Request object across the handoff.
                obs.flow("t", obs.flow_id(req.trace[0]))
        if obs.REQLOG.enabled:
            # Close the ledger's handoff segment (parked → adopted).
            obs.REQLOG.resume(req.uid)

    # -- the split tick loop ----------------------------------------------

    def serve(self, requests: Union[Sequence[Request], RequestSource],
              max_ticks: Optional[int] = None) -> ServeReport:
        """Run both workers' tick loops, interleaved, until the source
        drains — the same contract as :meth:`SlotServer.serve` (static
        trace or live source, control sweep at tick start, ``max_ticks``
        bounds runaway loops), with each loop iteration running at most
        one prefill-worker tick and one decode-worker tick.

        MAINTENANCE NOTE: the ingest/control-sweep/admission sections and
        the two dispatch bodies below deliberately MIRROR
        ``SlotServer.serve`` (specialized: no decode rows in the prefill
        tick, no chunk rows in the decode tick) rather than extracting
        shared helpers from the fused engine's hot loop. A behavioral fix
        to the fused engine's sweep ordering, cancel-carry TTL, deferral
        latch, or verify-tick packing must be ported here by hand — the
        token-parity gate catches data-plane drift but NOT control-plane
        drift (cancel/deadline race semantics). Grep anchor:
        engine.py's serve() carries the same section comments."""
        pf, dc = self.prefill, self.decode
        # The two workers' caches are views of ONE donated pool array
        # set: a dispatch through either consumes the other's view until
        # _relay_pool rebinds it (machine-checked by the donation-safety
        # lint pass through this declaration).
        # lint: donated-alias[pf.cache, dc.cache]
        live = isinstance(requests, RequestSource)
        if live:
            source: RequestSource = requests
        else:
            for r in requests:
                pf._validate(r)
            source = StaticRequestSource(requests)
            with self._lock:
                # Same reset rule as the fused engine: a stale mailbox
                # must not cancel a fresh synthetic trace; live sources
                # keep pre-loop drains/cancels.
                self._cancel_uids.clear()
                self._draining = False
        pending: deque = deque()
        cancel_carry: Dict[int, int] = {}
        results: Any = deque(maxlen=4096) if live else []
        visible_wall: Dict[int, float] = {}
        tbt: Any = deque(maxlen=1 << 16) if live else []
        # Loop-local run state (deliberately NOT instance attributes: the
        # serve loop is single-threaded and this state dies with the run).
        handoff_fifo: List[int] = []  # prefill slots parked in "handoff"
        pending_reset: Dict[int, int] = {}  # decode slot -> adopted length
        tok_dirty = False  # decode token vector needs a host->device push
        tick = 0
        decode_ticks = 0
        occupancy = 0
        tokens = 0
        queue_peak = 0
        prefill_s = 0.0  # serialized wall time per worker (the CPU-proxy
        decode_s = 0.0   # attribution record — see the module docstring)
        handoffs0 = self.handoffs
        transferred0 = self.pool.transferred
        peak_used = self.pool.used
        prefix0 = (self.prefix_index.stats()
                   if self.prefix_index is not None else None)
        host0 = (self.host_pool.stats()
                 if self.host_pool is not None else None)
        hit_bytes0 = pf._hit_bytes_moved
        spec0 = (dc._spec_proposed, dc._spec_accepted, dc._spec_ticks,
                 dc._spec_verifies)
        pf._defer_gen = -1  # a stale latch must not defer a fresh run
        t0 = time.monotonic()

        try:
            while True:
                if max_ticks is not None and tick >= max_ticks:
                    raise RuntimeError(
                        f"DisaggServer.serve() exceeded max_ticks="
                        f"{max_ticks} with {len(pending)} pending and "
                        f"{len(handoff_fifo)} queued-for-handoff "
                        f"request(s)"
                    )
                now = time.monotonic()
                pf._tick_prefix_hits = 0
                pf._tick_prefix_reused = 0
                pf._tick_restored = 0
                # Robustness-arc counters mirror the fused engine's (the
                # prefill worker holds the pair's sweep stats; the flight
                # record below surfaces them like SlotServer.serve does).
                pf._tick_cancelled = 0
                pf._tick_deadline = 0
                pf._tick_shed = 0

                # Ingest newly visible requests (live invalids finish
                # with outcome 'error'; static traces validated up front).
                # lint: mirror[ingest] begin
                for r in source.poll(tick):
                    vis = r.visible_at if r.visible_at is not None else now
                    try:
                        pf._validate(r)
                    except ValueError as e:
                        log.warning("rejecting request %s: %s", r.uid, e)
                        pf._finish_unadmitted(
                            r, tick, OUTCOME_ERROR, results, vis, now
                        )
                        continue
                    pending.append(r)
                    visible_wall[r.uid] = vis
                    if obs.TRACER.active:
                        obs.instant("request_queued", cat="serving",
                                    args={"rid": r.uid, "tick": tick})
                # lint: mirror[ingest] end

                # Control sweep — the fused engine's ordering (cancel
                # beats deadline beats drain-shed), applied across BOTH
                # workers; a request parked for handoff is a prefill-slot
                # occupant and retires through that worker's one retire
                # path like every other arc.
                cancels, draining = self._take_control()
                cancels |= set(cancel_carry)
                if cancels:
                    # lint: mirror[cancel-queued] begin
                    matched = set()
                    for r in [r for r in pending if r.uid in cancels]:
                        pending.remove(r)
                        matched.add(r.uid)
                        pf._tick_cancelled += 1
                        pf._finish_unadmitted(
                            r, tick, OUTCOME_CANCELLED, results,
                            visible_wall.pop(r.uid, now), now,
                        )
                    # lint: mirror[cancel-queued] end
                    for eng in (pf, dc):
                        for i, rq in enumerate(eng._slot_req):
                            if rq is not None and rq.uid in cancels:
                                matched.add(rq.uid)
                                pf._tick_cancelled += 1
                                eng._retire(i, tick, OUTCOME_CANCELLED,
                                            results)
                    # lint: mirror[cancel-carry] begin
                    for uid in cancels - matched:
                        if uid not in cancel_carry:
                            cancel_carry[uid] = 2
                        else:
                            cancel_carry[uid] -= 1
                            if cancel_carry[uid] <= 0:
                                del cancel_carry[uid]
                    for uid in matched:
                        cancel_carry.pop(uid, None)
                    # lint: mirror[cancel-carry] end
                # lint: mirror[deadline-queued] begin
                for r in [r for r in pending
                          if r.deadline_s is not None
                          and now >= r.deadline_s]:
                    pending.remove(r)
                    pf._tick_deadline += 1
                    pf._finish_unadmitted(
                        r, tick, OUTCOME_DEADLINE, results,
                        visible_wall.pop(r.uid, now), now,
                    )
                # lint: mirror[deadline-queued] end
                for eng in (pf, dc):
                    for i, rq in enumerate(eng._slot_req):
                        if (rq is not None and rq.deadline_s is not None
                                and now >= rq.deadline_s):
                            pf._tick_deadline += 1
                            eng._retire(i, tick, OUTCOME_DEADLINE, results)
                # The sweep may have retired parked requests out of their
                # slots — drop them from the handoff FIFO.
                handoff_fifo = [p for p in handoff_fifo
                                if pf._slot_state[p] == "handoff"]
                if draining:
                    # lint: mirror[drain-shed] begin
                    source.close()
                    while pending:
                        r = pending.popleft()
                        pf._tick_shed += 1
                        pf._finish_unadmitted(
                            r, tick, OUTCOME_SHED, results,
                            visible_wall.pop(r.uid, now), now,
                        )
                    # lint: mirror[drain-shed] end

                # Copy-on-write fork arc (ISSUE 15): mailboxed
                # fork(uid)s branch live requests onto free slots
                # (deferred ones retry from the carry for a few sweeps).
                # lint: mirror[fork] begin
                forks = self._take_forks()
                if forks or self._fork_carry:
                    self._apply_forks(forks, tick, pending)
                # lint: mirror[fork] end

                # Adopt: oldest parked request per free decode slot —
                # the zero-copy handoff step.
                free_d = dc._free_slots()
                while handoff_fifo and free_d:
                    p = handoff_fifo.pop(0)
                    d = free_d.pop(0)
                    self._adopt(p, d, tick, pending_reset)
                    tok_dirty = True

                # Admit: oldest visible request per free PREFILL slot
                # (worst-case reservation against the shared pool; the
                # generation latch and FIFO-no-skip rules are the fused
                # engine's).
                free = pf._free_slots()
                while free and pending:
                    if pf._staged_prefill and pf._prefill_fifo:
                        break
                    if pf._defer_gen == self.pool.gen:
                        break
                    resv = pf._paged_reserve(pending[0])
                    if resv is None:
                        pf._defer_gen = self.pool.gen
                        break
                    req = pending.popleft()
                    slot = free.pop(0)
                    pf._admit(req, slot, tick,
                              visible_wall.pop(req.uid, now), resv)
                if self.host_pool is not None and pf._tick_restored:
                    # A hit on a demoted path just scattered restored
                    # blocks into the (donated) pool arrays — relay so
                    # the decode worker's next dispatch sees them.
                    self._relay_pool(pf, dc)
                queue_depth = len(pending)
                if len(handoff_fifo) > queue_peak:
                    queue_peak = len(handoff_fifo)
                if obs.REGISTRY.enabled:
                    _HANDOFF_QUEUE.set(len(handoff_fifo))

                busy = bool(
                    pending or handoff_fifo
                    or not pf.all_slots_free or not dc.all_slots_free
                )
                if not busy:
                    # Idle handling stays BEFORE the tick body (the
                    # executed-ticks == recorded-ticks invariant).
                    if FLIGHT.enabled:
                        rec = None
                        # lint: mirror[sweep-only] begin
                        if (pf._tick_cancelled or pf._tick_deadline
                                or pf._tick_shed):
                            # The sweep retired work and left the tick
                            # idle; without this record the counters are
                            # zeroed at the next tick top and the storm
                            # vanishes from the black box.
                            rec = {
                                "tick": tick,
                                "sweep_only": True,
                                "occupancy": 0,
                                "queue_depth": queue_depth,
                                "pending": len(pending),
                                "cancelled": pf._tick_cancelled,
                                "deadline_expired": pf._tick_deadline,
                                "shed": pf._tick_shed,
                                "draining": draining,
                            }
                        # lint: mirror[sweep-only] end
                        if rec is not None:
                            rec["worker"] = "prefill"
                            FLIGHT.record(rec)
                    # lint: mirror[idle] begin
                    if source.exhausted or draining:
                        break
                    nxt = source.next_arrival()
                    if nxt is not None:
                        tick = max(tick + 1, nxt)
                    else:
                        if FLIGHT.enabled:
                            FLIGHT.mark_idle()
                        source.wait(0.05)
                    continue
                    # lint: mirror[idle] end

                # ---- prefill-worker tick: chunks only, no decode rows.
                tp0 = time.monotonic()
                plan = pf._plan_chunks()
                chunk_tokens = sum(n for _, n, _ in plan)
                pf_span = obs.span(
                    "disagg:prefill_tick", cat="serving",
                    args=None if not obs.TRACER.active else {
                        "tick": tick,
                        "prefilling": len(pf._prefill_fifo),
                        "chunk_tokens": chunk_tokens,
                        "handoff_queue": len(handoff_fifo),
                        "queue_depth": queue_depth,
                    },
                )
                with pf_span:
                    if pf._staged_prefill and plan:
                        # int8: staged exact chunks; the final chunk
                        # quantizes + inserts through the slot's table.
                        for slot, n, last in plan:
                            pf._run_staged_chunk(slot, n, last)
                        self._relay_pool(pf, dc)
                    elif plan:
                        tq = pf._chunk_bucket(max(n for _, n, _ in plan))
                        mat = np.zeros((pf.slots, tq), np.int32)
                        n_vec = np.zeros((pf.slots,), np.int32)
                        reset = np.zeros((pf.slots,), bool)
                        reset_val = np.zeros((pf.slots,), np.int32)
                        emit = np.zeros((pf.slots,), bool)
                        for slot, n, last in plan:
                            pf._ensure_blocks(
                                slot, pf._prefill_pos[slot] + n
                            )
                            rows, first = pf._consume_chunk(slot, n, last)
                            mat[slot, :n] = rows
                            n_vec[slot] = n
                            reset[slot] = first
                            reset_val[slot] = pf._prefill_start[slot]
                            emit[slot] = last
                        sidx = np.zeros((pf.slots,), np.int32)
                        pf._sync_table()
                        pf.tok, pf._lp, _, _, pf.cache = pf._mixed(
                            pf.params, jnp.asarray(mat),
                            jnp.asarray(n_vec), jnp.asarray(reset),
                            jnp.asarray(reset_val), jnp.asarray(emit),
                            pf.cache, pf._keys,
                            jnp.asarray(pf._temp_np),
                            jnp.asarray(pf._topk_np),
                            jnp.asarray(sidx), pf._lp,
                        )
                        self._relay_pool(pf, dc)
                        if pf._prefix is not None:
                            for slot, n, last in plan:
                                if last:
                                    pf._publish_prefix(slot)
                    awaits = [i for i, st in enumerate(pf._slot_state)
                              if st == "await"]
                    if awaits:
                        # lint: allow[host-sync] the prefill worker's one per-tick fetch (final-chunk first tokens + logprobs)
                        pf._tok_host = np.asarray(pf.tok)
                        # lint: allow[host-sync] rides the same sync point (first-token logprobs)
                        pf._lp_host = np.asarray(pf._lp)
                        now2 = time.monotonic()
                        for i in awaits:
                            req = pf._slot_req[i]
                            first = int(pf._tok_host[i])
                            pf._slot_tokens[i] = [first]
                            pf._slot_cum_lp[i] = float(pf._lp_host[i])
                            pf._push_token(req, first)
                            _, vis = pf._slot_admit[i]
                            pf._slot_ttft[i] = max(now2 - vis, 0.0)
                            pf._last_tok_t[i] = now2
                            tokens += 1
                            self.slo.observe_ttft(pf._slot_ttft[i])
                            if obs.REGISTRY.enabled:
                                _TOKENS.inc()
                                _TTFT.observe(pf._slot_ttft[i])
                            if obs.TRACER.active:
                                obs.instant(
                                    "first_token", cat="serving", args={
                                        "rid": req.uid, "slot": i,
                                        "tick": tick,
                                        "ttft_s": round(
                                            pf._slot_ttft[i], 6),
                                    })
                            if obs.REQLOG.enabled:
                                obs.REQLOG.first_token(req.uid, now=now2)
                            if req.eos_id is not None \
                                    and first == req.eos_id:
                                pf._retire(i, tick, OUTCOME_EOS, results)
                            elif req.max_new_tokens <= 1:
                                pf._retire(i, tick, OUTCOME_BUDGET,
                                           results)
                            else:
                                pf._slot_state[i] = "handoff"
                                handoff_fifo.append(i)
                                if len(handoff_fifo) > queue_peak:
                                    queue_peak = len(handoff_fifo)
                                if obs.TRACER.active:
                                    obs.instant(
                                        "handoff_queued", cat="serving",
                                        args={"rid": req.uid, "slot": i,
                                              "tick": tick})
                                if obs.REQLOG.enabled:
                                    # Open the ledger's handoff segment:
                                    # parked until a decode slot adopts.
                                    obs.REQLOG.park(req.uid)
                dt_pf = time.monotonic() - tp0
                prefill_s += dt_pf
                # CPU-proxy attribution: the serialized prefill section
                # must not count against decode-pool inter-token gaps —
                # shift every live decode slot's last-token clock past it
                # (see the module docstring; the serialized totals stay
                # in ServeReport.handoff).
                for i, st in enumerate(dc._slot_state):
                    if st == "live":
                        dc._last_tok_t[i] += dt_pf
                if FLIGHT.enabled:
                    FLIGHT.record({
                        "worker": "prefill",
                        "tick": tick,
                        "t_s": round(now - t0, 6),
                        "states": list(pf._slot_state),
                        "chunk_plan": [[s, int(n), bool(last)]
                                       for s, n, last in plan],
                        "chunk_tokens": chunk_tokens,
                        "handoff_queue": len(handoff_fifo),
                        "pending": len(pending),
                        "queue_depth": queue_depth,
                        "prefix_hits": pf._tick_prefix_hits,
                        "prefix_reused": pf._tick_prefix_reused,
                        # Robustness arcs this tick (the fused engine's
                        # black-box keys — a storm reads the same way).
                        "cancelled": pf._tick_cancelled,
                        "deadline_expired": pf._tick_deadline,
                        "shed": pf._tick_shed,
                        **({"restored_blocks": pf._tick_restored,
                            "host_blocks_used": self.host_pool.used}
                           if self.host_pool is not None else {}),
                        "draining": draining,
                    })

                # ---- decode-worker tick: Tq=1 / speculative verify only.
                td0 = time.monotonic()
                live_idx = [i for i, st in enumerate(dc._slot_state)
                            if st == "live"]
                tokens_this_tick = 0
                if obs.REGISTRY.enabled:
                    _SLOTS_OCCUPIED.set(len(live_idx))
                dc_span = obs.span(
                    "disagg:decode_tick", cat="serving",
                    args=None if not obs.TRACER.active else {
                        "tick": tick, "occupancy": len(live_idx),
                    },
                )
                with dc_span:
                    if live_idx and dc._speculate:
                        spec_plan: Dict[int, PackedSpec] = {}
                        for i in live_idx:
                            spec_plan[i] = dc._draft_slot(i)
                        rows_max = max(p.rows for p in spec_plan.values())
                        tq = (dc._spec_bucket(rows_max) if rows_max > 1
                              else 1)
                        mat = np.zeros((dc.slots, tq), np.int32)
                        n_vec = np.zeros((dc.slots,), np.int32)
                        reset = np.zeros((dc.slots,), bool)
                        reset_val = np.zeros((dc.slots,), np.int32)
                        emit = np.zeros((dc.slots,), bool)
                        use_dev0 = np.zeros((dc.slots,), bool)
                        # Per-ROW key-chain operands (ISSUE 20): decode-
                        # worker verify rows always ride the slot's own
                        # spec chain (branch < 0), stream index = emitted
                        # count + row depth — same fill as the fused
                        # engine's spec tick.
                        sidx = np.asarray(
                            [len(t) for t in dc._slot_tokens], np.int32
                        )
                        branch_m = np.full((dc.slots, tq), -1, np.int32)
                        ridx_m = sidx[:, None] + np.tile(
                            np.arange(tq, dtype=np.int32),
                            (dc.slots, 1),
                        )
                        need_tree = False
                        for i, pack in spec_plan.items():
                            r = pack.rows
                            dc._ensure_blocks(i, dc._slot_clen[i] + r)
                            mat[i, :r] = pack.row_tokens
                            n_vec[i] = r
                            # reset_val IS both the spec rollback and the
                            # adoption length fix (clen == plen there).
                            reset[i] = True
                            reset_val[i] = dc._slot_clen[i]
                            ridx_m[i, :r] = sidx[i] + pack.depth
                            if not np.array_equal(
                                pack.depth, np.arange(r, dtype=np.int32)
                            ):
                                need_tree = True
                        pending_reset.clear()
                        dc._sync_table()
                        if tok_dirty:
                            dc.tok = jnp.asarray(dc._tok_host)
                            tok_dirty = False
                        args = (
                            dc.params, jnp.asarray(mat), dc.tok,
                            jnp.asarray(use_dev0), jnp.asarray(n_vec),
                            jnp.asarray(reset), jnp.asarray(reset_val),
                            jnp.asarray(emit),
                        )
                        extra = (
                            dc._keys, jnp.asarray(dc._temp_np),
                            jnp.asarray(dc._topk_np),
                            jnp.asarray(sidx), dc._lp,
                            jnp.asarray(dc._salt_np),
                            jnp.asarray(branch_m), jnp.asarray(ridx_m),
                        )
                        if need_tree:
                            depth_m = np.tile(
                                np.arange(tq, dtype=np.int32),
                                (dc.slots, 1),
                            )
                            bits_m = np.broadcast_to(
                                np.tril(np.ones((tq, tq), bool)),
                                (dc.slots, tq, tq),
                            ).copy()
                            for i, pack in spec_plan.items():
                                r = pack.rows
                                depth_m[i, :r] = pack.depth
                                bits_m[i, :r, :r] = pack.anc
                            dc.tok, dc._lp, fused_dev, _, dc.cache = \
                                dc._spec_tree(
                                    *args, jnp.asarray(depth_m),
                                    jnp.asarray(bits_m), dc.cache,
                                    *extra,
                                )
                        else:
                            dc.tok, dc._lp, fused_dev, _, dc.cache = \
                                dc._spec_lin(
                                    *args, dc.cache, *extra,
                                )
                        # lint: allow[host-sync] the decode worker's one per-tick fetch (fused token/logprob vectors + every verify-row draw)
                        fused_host = np.asarray(fused_dev)
                        dc._tok_host = fused_host[:, 0, 0]
                        dc._lp_host = np.ascontiguousarray(
                            fused_host[:, 0, 1]
                        ).view(np.float32)
                        alltok_host = fused_host[:, 1:, 0]
                        alllp_host = np.ascontiguousarray(
                            fused_host[:, 1:, 1]
                        ).view(np.float32)
                        now2 = time.monotonic()
                        decode_ticks += 1
                        occupancy += len(live_idx)
                        n_new = dc._spec_commit_all(
                            spec_plan, alltok_host, alllp_host, tq, now2,
                            tick, results, tbt,
                        )
                        tokens += n_new
                        tokens_this_tick += n_new
                        # The commit may have dispatched a compaction —
                        # relay after, not before.
                        self._relay_pool(dc, pf)
                    elif live_idx:
                        n_vec = np.zeros((dc.slots,), np.int32)
                        emit = np.zeros((dc.slots,), bool)
                        reset = np.zeros((dc.slots,), bool)
                        reset_val = np.zeros((dc.slots,), np.int32)
                        n_vec[live_idx] = 1
                        emit[live_idx] = True
                        for i, plen in pending_reset.items():
                            # The one decode dispatch where the device
                            # learns an adopted slot's length.
                            if dc._slot_state[i] == "live":
                                reset[i] = True
                                reset_val[i] = plen
                        pending_reset.clear()
                        for i in list(dc._live_reset):
                            # A forked child's device length learns the
                            # fork point at its first consuming tick
                            # (mirrors the fused engine's fork resets).
                            if dc._slot_state[i] == "live":
                                reset[i] = True
                                reset_val[i] = dc._live_reset.pop(i)
                        for i in live_idx:
                            dc._ensure_blocks(
                                i, len(dc._slot_req[i].prompt)
                                + len(dc._slot_tokens[i])
                            )
                        sidx = np.asarray(
                            [len(t) for t in dc._slot_tokens], np.int32
                        )
                        dc._sync_table()
                        if tok_dirty:
                            dc.tok = jnp.asarray(dc._tok_host)
                            tok_dirty = False
                        dc.tok, dc._lp, fused_dev, _, dc.cache = dc._mixed(
                            dc.params, dc.tok[:, None],
                            jnp.asarray(n_vec), jnp.asarray(reset),
                            jnp.asarray(reset_val), jnp.asarray(emit),
                            dc.cache, dc._keys,
                            jnp.asarray(dc._temp_np),
                            jnp.asarray(dc._topk_np),
                            jnp.asarray(sidx), dc._lp,
                        )
                        self._relay_pool(dc, pf)
                        # lint: allow[host-sync] the decode worker's one per-tick fetch (token vector + bitcast logprobs, one fused array)
                        fh = np.asarray(fused_dev)
                        dc._tok_host = fh[:, 0]
                        dc._lp_host = np.ascontiguousarray(
                            fh[:, 1]
                        ).view(np.float32)
                        now2 = time.monotonic()
                        decode_ticks += 1
                        occupancy += len(live_idx)
                        for i in live_idx:
                            req = dc._slot_req[i]
                            tok_i = int(dc._tok_host[i])
                            # Every live decode slot has a first token
                            # already (handoff adoption, or the fork's
                            # family pass) — always an inter-token gap.
                            dc._slot_tokens[i].append(tok_i)
                            dc._slot_cum_lp[i] += float(dc._lp_host[i])
                            dc._push_token(req, tok_i, dc._slot_index[i])
                            tokens += 1
                            tokens_this_tick += 1
                            gap = max(now2 - dc._last_tok_t[i], 0.0)
                            tbt.append(gap)
                            dc._last_tok_t[i] = now2
                            if gap > dc._slot_max_tbt[i]:
                                dc._slot_max_tbt[i] = gap
                            self.slo.observe_tbt(gap)
                            if obs.REGISTRY.enabled:
                                _TOKENS.inc()
                                _TBT.observe(gap)
                            if (req.fork_at is not None
                                    and dc._slot_index[i] == 0
                                    and len(dc._slot_tokens[i])
                                    == req.fork_at):
                                # Replayable mid-generation branch: the
                                # request forks itself through the
                                # pair's mailbox (applied on this
                                # worker at the next sweep).
                                self.fork(req.uid)
                            if req.eos_id is not None \
                                    and tok_i == req.eos_id:
                                dc._retire(i, tick, OUTCOME_EOS, results)
                            elif (len(dc._slot_tokens[i])
                                    >= req.max_new_tokens):
                                dc._retire(i, tick, OUTCOME_BUDGET,
                                           results)
                decode_s += time.monotonic() - td0
                if self.pool.used > peak_used:
                    peak_used = self.pool.used
                self.pool.publish_gauges()
                if self.host_pool is not None:
                    # The pair's staged D2H flush point (mirrors the
                    # fused engine's end-of-tick flush): both workers
                    # have dispatched, the relayed pool arrays are
                    # current, and the fetch overlaps the loop's idle
                    # gap toward the next tick's host work.
                    pf._flush_demotions()
                    self.host_pool.publish_gauge()
                if FLIGHT.enabled:
                    FLIGHT.record({
                        "worker": "decode",
                        "tick": tick,
                        "t_s": round(now - t0, 6),
                        "occupancy": len(live_idx),
                        "states": list(dc._slot_state),
                        "tokens_emitted": tokens_this_tick,
                        "handoff_queue": len(handoff_fifo),
                        "kv_blocks_used": self.pool.used,
                        "kv_blocks_free": self.pool.free_count,
                        "draining": draining,
                    })
                self.slo.maybe_export(now)
                tick += 1
        except BaseException as e:
            FLIGHT.dump_if_armed(f"disagg_error:{type(e).__name__}")
            if obs.TRACER.active:
                obs.instant("engine_error", cat="serving", args={
                    "error": type(e).__name__, "tick": tick,
                })
            raise

        if self.host_pool is not None:
            # A drained run leaves no demotion staged: the ledger's
            # _DEMOTED blocks would otherwise read as leaked capacity.
            pf._flush_demotions()
            self.host_pool.publish_gauge()
        if FLIGHT.enabled:
            FLIGHT.mark_idle()
        with self._lock:
            self._cancel_uids.clear()
            self._draining = False
        wall = time.monotonic() - t0
        self.slo.export_gauges()
        slo_snap = self.slo.snapshot()
        prefix_snap: Dict[str, Any] = {}
        if self.prefix_index is not None:
            p1 = self.prefix_index.stats()
            reused = p1["tokens_reused"] - prefix0["tokens_reused"]
            prompt_tokens = sum(r.prompt_len for r in results)
            prefix_snap = {
                "hits": p1["hits"] - prefix0["hits"],
                "misses": p1["misses"] - prefix0["misses"],
                "tokens_reused": reused,
                "reused_ratio": round(reused / prompt_tokens, 4)
                if prompt_tokens else 0.0,
                "evictions": p1["evictions"] - prefix0["evictions"],
                "pool_blocks_used": p1["pool_blocks_used"],
                "pool_blocks": p1["pool_blocks"],
                # Reference-in-place for exact blocks; int8 hits count
                # their dequant gather into staging (ISSUE 13).
                "hit_bytes_moved": pf._hit_bytes_moved - hit_bytes0,
            }
        kv_snap = {
            "layout": "paged",
            "block": self.kv_block,
            "pool_blocks": self.kv_blocks,
            "blocks_used": self.pool.used,
            "blocks_free": self.pool.free_count,
            "peak_blocks_used": peak_used,
        }
        if self.host_pool is not None:
            h1 = self.host_pool.stats()
            kv_snap.update({
                "host_blocks": h1["host_blocks"],
                "host_blocks_used": h1["host_blocks_used"],
                "demotions": h1["demotions"] - host0["demotions"],
                "restores": h1["restores"] - host0["restores"],
                "host_drops": h1["host_drops"] - host0["host_drops"],
            })
        handoff_snap = {
            "handoffs": self.handoffs - handoffs0,
            "blocks_transferred": self.pool.transferred - transferred0,
            "queue_peak": queue_peak,
            "kv_bytes_moved": 0,  # the in-process contract, audited by
            # transfer_private: ownership moves, the bytes do not
            "prefill_tick_s": round(prefill_s, 4),
            "decode_tick_s": round(decode_s, 4),
        }
        spec_snap: Dict[str, Any] = {}
        if dc._speculate:
            prop = dc._spec_proposed - spec0[0]
            acc = dc._spec_accepted - spec0[1]
            spec_snap = {
                "drafter": type(dc._drafter).__name__,
                "draft_k": dc.draft_k,
                "proposed": prop,
                "accepted": acc,
                "acceptance_rate": round(acc / prop, 4) if prop else 0.0,
                "verify_ticks": dc._spec_ticks - spec0[2],
                "tokens_per_verify": round(
                    1.0 + acc / (dc._spec_verifies - spec0[3]), 4
                ) if dc._spec_verifies - spec0[3] else 0.0,
            }
        log.info(
            "disagg served %d request(s): %d tokens, %d handoff(s), "
            "%d decode tick(s), %.1f tok/s, mean decode occupancy "
            "%.2f/%d",
            len(results), tokens, self.handoffs - handoffs0,
            decode_ticks, tokens / wall if wall > 0 else 0.0,
            occupancy / max(decode_ticks, 1), dc.slots,
        )
        return ServeReport(
            results=sorted(results, key=lambda r: r.uid),
            ticks=tick,
            wall_s=wall,
            tokens_generated=tokens,
            mean_occupancy=occupancy / max(decode_ticks, 1),
            tbt_s=list(tbt),
            slo=slo_snap,
            prefix=prefix_snap,
            kv=kv_snap,
            spec=spec_snap,
            handoff=handoff_snap,
            requests=obs.aggregate_ledgers(
                [r.ledger for r in results if r.ledger is not None]
            ) or {},
        )
