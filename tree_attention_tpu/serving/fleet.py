"""Fleet supervision: N replica engines behind one cache-aware router.

:mod:`~tree_attention_tpu.serving.router` is the routing brain; this
module is the *lifecycle* around it — the piece that turns one ingress
into a supervised fleet (ISSUE 11):

- :class:`LocalReplica` — one in-process :class:`SlotServer` +
  :class:`IngressServer` pair. Restart reuses the warmed engine (the
  serve loop is reusable by contract — a drained engine serves again
  without recompiling), so a rolling restart of an in-process fleet
  costs milliseconds, not a jit recompile. This is the CLI's
  ``--serve-fleet`` shape and the one the tier-1 integration test
  drives.
- :class:`ProcessReplica` — one replica as a child process running the
  CLI's ``--serve-http`` mode, supervised with the gang-lifecycle
  conventions :mod:`~tree_attention_tpu.host_runtime` established:
  SIGTERM-then-SIGKILL grace escalation on shutdown, exit statuses
  classified through the same ``ok/crash/deadline/stall`` vocabulary
  (:func:`~tree_attention_tpu.host_runtime._rank_exit_outcome`, the
  supervisor's 124/125/128+sig conventions), and a per-replica restart
  budget — the elastic-recovery idiom, per replica instead of
  whole-gang because replicas are independent (no collective to wedge).
- :class:`FleetSupervisor` — owns the replicas and the router: starts
  everything, health-polls replicas on a monitor thread (a dead replica
  is marked down in the router and restarted while budget lasts), and
  implements **rolling restart without drops**: drain one replica
  (router stops routing to it; its queued work sheds and the router
  requeues those requests on peers; in-flight streams finish), restart
  it, wait for readiness, rejoin it with a cleared affinity tree — then
  the next replica. At no point is an accepted request lost.

Threading contract: the supervisor's state is shared between its public
API (caller thread), the monitor thread, and nothing else — mutations
happen under ``self._lock`` (the invariant linter's lock-safety pass
scopes this file). Replicas' own state likewise. HTTP and process I/O
run outside the locks.
"""

from __future__ import annotations

import json
import subprocess
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from tree_attention_tpu.host_runtime import _rank_exit_outcome
from tree_attention_tpu.serving.ingress import IngressServer
from tree_attention_tpu.serving.router import FleetRouter
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.fleet")


class LocalReplica:
    """In-process replica: one engine + one ingress on a loopback port.

    ``engine_factory`` is called once, lazily at first start; restarts
    wrap the SAME engine in a fresh :class:`IngressServer` (new port —
    the supervisor re-registers it with the router). The engine's radix
    cache therefore *survives* an in-process restart; the router still
    clears its affinity tree on rejoin, which is merely conservative
    (affinity re-learns in one request per prefix).
    """

    def __init__(self, name: str, engine_factory: Callable[[], Any], *,
                 max_queue: int = 64,
                 default_deadline_s: Optional[float] = None,
                 default_max_tokens: int = 16,
                 keepalive_s: float = 0.5):
        self.name = name
        self.metrics_url: Optional[str] = None  # in-process replicas
        # share the router's registry; there is nothing to federate
        self._factory = engine_factory
        self._ingress_kw = dict(
            max_queue=max_queue,
            default_deadline_s=default_deadline_s,
            default_max_tokens=default_max_tokens,
            keepalive_s=keepalive_s,
        )
        self._lock = threading.RLock()
        self._engine: Optional[Any] = None
        self._ingress: Optional[IngressServer] = None

    @property
    def engine(self):
        with self._lock:
            if self._engine is None:
                self._engine = self._factory()
            return self._engine

    @property
    def port(self) -> int:
        with self._lock:
            return 0 if self._ingress is None else self._ingress.port

    def start(self) -> int:
        engine = self.engine  # build outside the assignment lock hold
        with self._lock:
            if self._ingress is not None and self._ingress.running:
                return self._ingress.port
            ing = IngressServer(engine, port=0, **self._ingress_kw)
            self._ingress = ing
        return ing.start()

    def ready(self) -> bool:
        with self._lock:
            ing = self._ingress
        return (ing is not None and ing.running and not ing.draining
                and ing.engine_error is None and ing.report is None)

    def begin_drain(self) -> None:
        with self._lock:
            ing = self._ingress
        if ing is not None:
            ing.drain()

    def await_drained(self, timeout_s: float = 60.0) -> bool:
        """Block until the engine loop returns its report, then tear the
        HTTP listener down; True iff it drained inside the timeout.

        On a timeout the listener is deliberately KEPT: the engine
        thread still owns the serve loop, and tearing down the ingress
        would let :meth:`restart`'s undrained guard pass — two
        concurrent serve() loops on one engine corrupt slot/pool state.
        A timed-out drain leaves the replica down-but-intact for a
        later retry."""
        with self._lock:
            ing = self._ingress
        if ing is None:
            return True
        report = ing.join(timeout=timeout_s)
        if report is None:
            return False  # engine loop still running: keep the guard up
        ing.stop()
        return True

    def restart(self) -> int:
        """Fresh ingress around the same warmed engine; returns the new
        port. The caller drains first — restarting an undrained replica
        raises (its engine thread still owns the serve loop)."""
        with self._lock:
            if self._ingress is not None and self._ingress.running:
                raise RuntimeError(
                    f"replica {self.name}: restart before drain "
                    f"(the engine thread still owns the serve loop)"
                )
            ing = IngressServer(self.engine, port=0, **self._ingress_kw)
            self._ingress = ing
        return ing.start()

    def stop(self) -> None:
        self.begin_drain()
        self.await_drained()

    def leak_report(self) -> Dict[str, int]:
        return self.engine.leak_report()


class ProcessReplica:
    """Child-process replica: the CLI's ``--serve-http`` under gang-style
    supervision (SIGTERM drain -> grace -> SIGKILL; exit statuses read
    through :func:`host_runtime._rank_exit_outcome`'s vocabulary).

    ``argv`` must put the ingress on a FIXED ``port`` (the parent cannot
    learn an OS-picked port from a child it only holds a PID for); pass
    ``metrics_port`` when the child exports ``--metrics-port`` so the
    router can federate its scrape.
    """

    def __init__(self, name: str, argv: Sequence[str], *, port: int,
                 host: str = "127.0.0.1",
                 metrics_port: Optional[int] = None,
                 grace_s: float = 5.0,
                 start_timeout_s: float = 120.0):
        if port < 1:
            raise ValueError(
                f"replica {name!r} needs a fixed port (got {port}); the "
                f"parent cannot discover a child's OS-picked port"
            )
        self.name = name
        self.argv = list(argv)
        self.host = host
        self._port = port
        self.metrics_url = (
            f"http://{host}:{metrics_port}/metrics"
            if metrics_port is not None else None
        )
        self.grace_s = grace_s
        self.start_timeout_s = start_timeout_s
        self._lock = threading.RLock()
        self._proc: Optional[subprocess.Popen] = None
        self.last_outcome: Optional[str] = None

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> int:
        import os

        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return self._port
            env = dict(os.environ)
            env["TA_REPLICA"] = self.name  # ps/log attribution, the
            # JAX_PROCESS_INDEX idiom of launch_local
            self._proc = subprocess.Popen(self.argv, env=env)
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            if self.ready():
                return self._port
            with self._lock:
                rc = self._proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f"replica {self.name} exited during startup "
                    f"({_rank_exit_outcome(rc)}, status {rc})"
                )
            time.sleep(0.2)
        raise RuntimeError(
            f"replica {self.name} not ready after {self.start_timeout_s}s"
        )

    def ready(self) -> bool:
        stats = self._stats()
        return bool(stats and stats.get("ready"))

    def _stats(self) -> Optional[Dict[str, Any]]:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{self.host}:{self._port}/ingress/stats",
                timeout=2.0,
            ) as r:
                return json.loads(r.read())
        except (OSError, ValueError):
            return None

    def begin_drain(self) -> None:
        """The drain handshake: POST /admin/drain, falling back to
        SIGTERM (the CLI installs install_drain_signals, so both spell
        the same graceful drain)."""
        import urllib.request

        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://{self.host}:{self._port}/admin/drain",
                method="POST", data=b""), timeout=2.0).read()
            return
        except (OSError, ValueError):
            pass
        with self._lock:
            proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.terminate()

    def await_drained(self, timeout_s: float = 60.0) -> bool:
        """Wait for the child to exit; escalate SIGTERM -> SIGKILL after
        the deadline + grace (the launcher's escalation shape). Always
        returns True — by then the process is GONE either way, so a
        restart is safe (the contract the supervisor checks); the exit
        classification lands in :attr:`last_outcome`
        (``ok/crash/deadline/stall``, the launcher vocabulary)."""
        with self._lock:
            proc = self._proc
        if proc is None:
            return True
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                rc = proc.wait(timeout=self.grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
        if rc < 0:
            rc = 128 - rc  # Popen reports -SIGNUM, the launcher's rule
        with self._lock:
            self.last_outcome = _rank_exit_outcome(rc)
        if rc != 0:
            log.warning("fleet: replica %s exited %s (status %d)",
                        self.name, _rank_exit_outcome(rc), rc)
        return True

    def restart(self) -> int:
        return self.start()

    def stop(self) -> None:
        self.begin_drain()
        self.await_drained()

    def alive(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None


class FleetSupervisor:
    """Start, watch, and roll a fleet of replicas behind one router.

    Args:
      replicas: the handles (Local or Process; mixable).
      router: a pre-built :class:`FleetRouter` (its ``block`` must match
        the replicas' prefix block), or None to build a default.
      monitor_interval_s: health-poll period; 0 disables the monitor
        thread entirely (tests drive lifecycle explicitly).
      restarts: per-replica restart budget for UNPLANNED deaths (the
        elastic-recovery idiom); rolling restarts are planned and do not
        consume it.
    """

    def __init__(self, replicas: Sequence[Any], *,
                 router: Optional[FleetRouter] = None,
                 monitor_interval_s: float = 1.0,
                 restarts: int = 1):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.replicas: Dict[str, Any] = {r.name: r for r in replicas}
        self.router = router if router is not None else FleetRouter()
        self.monitor_interval_s = monitor_interval_s
        self.restarts = restarts
        self._lock = threading.RLock()
        # Serializes whole drain/restart SEQUENCES (monitor recovery vs
        # rolling restart) — self._lock only guards state snapshots, so
        # without this a monitor poll could observe a mid-roll replica
        # as unhealthy and race a second restart into it.
        self._op_lock = threading.Lock()
        self._maintenance: set = set()  # replicas mid-rolling-restart
        self._restarts_used: Dict[str, int] = {n: 0 for n in names}
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> int:
        """Start every replica, register them, start the router (and the
        monitor); returns the router's port."""
        for name, rep in sorted(self.replicas.items()):
            port = rep.start()
            self.router.add_replica(name, port,
                                    metrics_url=rep.metrics_url)
            log.info("fleet: replica %s up on port %d", name, port)
        port = self.router.start()
        if self.monitor_interval_s > 0:
            with self._lock:
                self._monitor = threading.Thread(
                    target=self._monitor_loop, name="fleet-monitor",
                    daemon=True,
                )
                self._monitor.start()
        log.info("fleet: router up on http://127.0.0.1:%d (%d replicas)",
                 port, len(self.replicas))
        return port

    def stop(self) -> None:
        """Graceful fleet shutdown: stop the monitor, drain every
        replica (concurrently), then the router."""
        self._stop_monitor.set()
        with self._op_lock:
            # Barrier: an in-flight _check_one recovery (drain up to
            # 30s + restart) must complete before the fleet drains, or
            # it would rejoin/restart a replica AFTER stop() returned —
            # a serve loop nothing will ever drain.
            pass
        with self._lock:
            mon = self._monitor
        if mon is not None:
            mon.join(timeout=60.0)
        for name in self.replicas:
            self.router.set_draining(name)
        for rep in self.replicas.values():
            rep.begin_drain()
        for rep in self.replicas.values():
            rep.await_drained()
        self.router.stop()

    # -- health monitor ---------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop_monitor.wait(self.monitor_interval_s):
            for name, rep in list(self.replicas.items()):
                with self._lock:
                    if name in self._maintenance:
                        continue
                self._check_one(name, rep)

    def _check_one(self, name: str, rep) -> None:
        if rep.ready():
            return
        with self._op_lock:
            # Re-check under the operation lock: a rolling restart may
            # have taken this replica into maintenance (or finished
            # healing it) between the monitor's poll and here — acting
            # on the stale observation would double-drain the replica
            # and burn the unplanned-restart budget on planned work.
            # A requested shutdown also beats recovery: stop()'s barrier
            # must not be followed by a resurrection.
            if self._stop_monitor.is_set():
                return
            with self._lock:
                if name in self._maintenance:
                    return
            # lint: allow[lock-order] _op_lock deliberately serializes whole recovery sequences (health probe included); state snapshots use self._lock, which never blocks
            if rep.ready():
                return
            self.router.mark_down(name)
            with self._lock:
                used = self._restarts_used.get(name, 0)
                if used >= self.restarts:
                    return
                self._restarts_used[name] = used + 1
            log.warning("fleet: replica %s unhealthy; restarting "
                        "(attempt %d/%d)", name, used + 1, self.restarts)
            try:
                # lint: allow[lock-order] the drain handshake must run under _op_lock — releasing it mid-recovery is exactly the double-drain race the lock exists to prevent
                rep.begin_drain()
                # lint: allow[lock-order] bounded in practice (30s drain + grace escalation to SIGKILL); _op_lock must be held or a rolling restart could double-serve the engine
                if not rep.await_drained(timeout_s=30.0):
                    # Wedged drain: the engine thread still owns its
                    # serve loop — restarting now would double-serve the
                    # engine. Leave it down; the next poll retries.
                    log.error("fleet: replica %s drain timed out; "
                              "leaving it down", name)
                    return
                # lint: allow[lock-order] restart-until-ready stays inside the serialized recovery section; start_timeout_s bounds it
                port = rep.restart()
            except (RuntimeError, OSError) as e:
                log.error("fleet: replica %s restart failed: %s", name, e)
                return
            self.router.rejoin(name, port=port, reset_tree=True)

    # -- rolling restart --------------------------------------------------

    def rolling_restart(self, *, drain_timeout_s: float = 60.0,
                        ready_timeout_s: float = 60.0) -> Dict[str, Any]:
        """Restart every replica, one at a time, with zero dropped
        accepted requests: the router stops routing to the victim, its
        queued work sheds replica-side and requeues router-side onto
        peers, its in-flight streams finish, then drain -> restart ->
        ready -> rejoin. Returns per-replica outcomes."""
        out: Dict[str, Any] = {}
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            with self._op_lock:
                # Mutual exclusion with the monitor's recovery path: a
                # concurrent unplanned restart of the SAME replica would
                # double-drain it.
                with self._lock:
                    self._maintenance.add(name)
            try:
                self.router.set_draining(name)
                rep.begin_drain()
                drained = rep.await_drained(timeout_s=drain_timeout_s)
                if not drained:
                    # The engine loop is wedged past the timeout:
                    # restarting would double-serve the engine. Mark it
                    # down (it takes no routes), move on — the fleet
                    # keeps serving on its peers.
                    self.router.mark_down(name)
                    out[name] = {"drained": False, "skipped": True}
                    log.error("fleet: rolling restart of %s aborted — "
                              "drain timed out; replica left down", name)
                    continue
                leak = (rep.leak_report()
                        if hasattr(rep, "leak_report") else None)
                port = rep.restart()
                deadline = time.monotonic() + ready_timeout_s
                while not rep.ready():
                    if time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"replica {name} not ready after restart"
                        )
                    time.sleep(0.05)
                self.router.rejoin(name, port=port, reset_tree=True)
                out[name] = {"drained": drained, "port": port,
                             **({"leak": leak} if leak else {})}
                log.info("fleet: rolled %s (drained=%s, new port %d)",
                         name, drained, port)
            finally:
                with self._lock:
                    self._maintenance.discard(name)
        return out

    # -- introspection ----------------------------------------------------

    def leak_reports(self) -> Dict[str, Dict[str, int]]:
        return {n: r.leak_report() for n, r in self.replicas.items()
                if hasattr(r, "leak_report")}

    @property
    def engines(self) -> List[Any]:
        """The in-process engines (LocalReplica fleets; bench/tests)."""
        return [r.engine for r in self.replicas.values()
                if isinstance(r, LocalReplica)]


def install_fleet_drain_signals(supervisor: FleetSupervisor
                                ) -> threading.Event:
    """SIGTERM/SIGINT -> set the returned event (main thread only).

    The ingress's :func:`install_drain_signals` drains one server from
    inside the handler because drain() is a quick flag flip; a fleet
    drain JOINS N engine loops, which must not run in a signal handler.
    So the handler only sets an event — the caller (the CLI's fleet
    loop) waits on it and runs :meth:`FleetSupervisor.stop` on the main
    thread. A second signal while draining escalates to the previous
    handler (an operator's double-SIGTERM must still kill a stuck
    drain), the same rule the ingress uses.
    """
    import signal

    evt = threading.Event()
    prev = {}

    def _begin_drain(signum, frame):
        if evt.is_set():
            handler = prev.get(signum)
            if callable(handler):
                handler(signum, frame)
            else:
                signal.signal(signum, signal.SIG_DFL)
                import os

                os.kill(os.getpid(), signum)
            return
        evt.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        prev[sig] = signal.signal(sig, _begin_drain)
    return evt
