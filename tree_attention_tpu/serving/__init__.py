"""Continuous-batching serving: slot scheduler over the ragged decode stack.

The decode stack serves one request shape (``models/decode.py``); this
package serves *traffic*: a fixed batch of S cache slots, a request queue,
and a tick loop that admits pending requests into free slots, runs ONE
compiled decode step for every live slot, and retires/refills slots the
moment a request finishes — static shapes throughout, so one compilation
serves every mixture of request states (the per-slot ``(B,)`` cache lengths
carry the raggedness as data, not shape).
"""

from tree_attention_tpu.serving.engine import (  # noqa: F401
    OUTCOMES,
    Request,
    RequestResult,
    RequestSource,
    ServeReport,
    SlotServer,
    StaticRequestSource,
    synthetic_trace,
)
from tree_attention_tpu.serving.block_pool import (  # noqa: F401
    BlockAllocator,
    ShardedBlockAllocator,
)
from tree_attention_tpu.serving.disagg import DisaggServer  # noqa: F401
from tree_attention_tpu.serving.fleet import (  # noqa: F401
    FleetSupervisor,
    LocalReplica,
    ProcessReplica,
)
from tree_attention_tpu.serving.router import (  # noqa: F401
    FleetRouter,
    ReplicaTree,
    federate_metrics,
)
from tree_attention_tpu.serving.prefix_cache import (  # noqa: F401
    PagedPrefixIndex,
    PrefixCache,
)
from tree_attention_tpu.serving.speculation import (  # noqa: F401
    DraftModelDrafter,
    DraftProposal,
    Drafter,
    PromptLookupDrafter,
    PromptLookupTreeDrafter,
    accept_longest_path,
    make_drafter,
    pack_proposal,
)
