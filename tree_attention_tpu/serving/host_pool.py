"""Host-RAM block tier under the device KV pool (ISSUE 13).

Device pool capacity is the hard ceiling on prefix retention: the radix
tree's LRU eviction *frees* refcount-0 blocks, so every evicted prefix
is a future cold prefill. This module turns that eviction into
**demotion** (SGLang's hierarchical-cache direction, extending
RadixAttention — arXiv:2312.07104): evicted blocks park in a
preallocated host-side block pool, the radix node keeps existing with a
``tier`` bit flipped to *host*, and a later prefix hit **restores** the
path with one batched H2D scatter into freshly allocated device blocks.
The effective prefix cache becomes host-RAM-sized; only the working set
pays device bytes.

Mechanics, in the order a block travels:

- **Demote (staged)**: the tree picks its LRU victim and calls
  :meth:`HostBlockPool.enqueue` — the device block enters the
  allocator's ``demoted`` ledger state (not reusable yet!) and a
  (host row ← device block) pair joins the pending queue. No device
  work happens here.
- **Flush**: the engine drains the pending queue OFF the tick — one
  jitted gather over the whole batch, one D2H fetch — then the device
  blocks finally free (:meth:`BlockAllocator.free_demoted`). A dry
  allocator can force a mid-tick flush, but the steady state is one
  batched gather per tick at most.
- **Restore**: a prefix hit on a demoted node either *cancels* a
  still-pending demotion (the device bytes never left — zero copies) or
  allocates a fresh device block from the admission's reservation and
  rides ONE batched H2D scatter for the whole path. Restore is
  bit-exact on the exact tier: the bytes are copied, not recomputed.
- **Drop**: a full host pool evicts ITS LRU refcount-0 leaf — the node
  disappears from the tree entirely, exactly like a classic eviction
  (the ``free→…→demoted→restored|dropped`` lifecycle in
  ARCHITECTURE.md).

Storage is plain page-locked-equivalent host memory (numpy arrays — on
a real TPU host you would back this with pinned allocations so the DMA
engine can stream it; the CPU proxy has no distinction). int8 pools
carry their per-block scale scalars alongside the KV bytes, so a
restored quantized block dequantizes exactly as it did before demotion.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from tree_attention_tpu import obs
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.host_pool")

_HOST_USED = obs.gauge(
    "serving_kv_host_blocks_used",
    "host-tier KV blocks currently holding a demoted span",
)
_DEMOTIONS = obs.counter(
    "serving_kv_demotions_total",
    "device KV blocks demoted toward the host tier (counted at enqueue)",
)
_RESTORES = obs.counter(
    "serving_kv_restores_total",
    "demoted KV blocks restored to the device tier (H2D copies and "
    "cancelled-pending restores both count — each was a device-capacity "
    "miss the host tier absorbed)",
)


class HostBlockPool:
    """A fixed pool of ``blocks`` host-RAM KV blocks + the staging queue.

    Args:
      blocks: host-tier capacity, in blocks (the ``--host-blocks`` knob).
      n_layers / n_kv_heads / block / d_head: the block geometry — must
        match the device pool's.
      dtype: the device pool's numpy dtype (``int8`` under quantized
        serving, the model dtype otherwise).
      quantized: also carry per-block scale scalars ``(L, Hkv)`` per
        block for K and V (the shareable-int8 contract, ISSUE 13).

    Single-threaded by design: every method runs on the engine loop
    thread (the ingress's thread-safe seams stop at the engine's control
    mailboxes). The D2H/H2D copies themselves are the CALLER's — this
    class only owns the host bytes and the pending bookkeeping, so it
    stays importable without jax.
    """

    def __init__(
        self,
        blocks: int,
        *,
        n_layers: int,
        n_kv_heads: int,
        block: int,
        d_head: int,
        dtype,
        quantized: bool = False,
    ):
        if blocks < 1:
            raise ValueError(f"host pool needs >= 1 block, got {blocks}")
        self.blocks = blocks
        self.block = block
        shape = (blocks, n_layers, n_kv_heads, block, d_head)
        self.k = np.zeros(shape, dtype)
        self.v = np.zeros(shape, dtype)
        self.quantized = quantized
        if quantized:
            sshape = (blocks, n_layers, n_kv_heads)
            self.k_scale = np.ones(sshape, np.float32)
            self.v_scale = np.ones(sshape, np.float32)
        self._free: List[int] = list(range(blocks - 1, -1, -1))
        # host row -> device block id, for demotions whose D2H copy has
        # not run yet (their canonical bytes are still on the device).
        self.pending: Dict[int, int] = {}
        # Lifetime accounting (the engine snapshots + diffs per run).
        self.demotions = 0
        self.restores = 0
        self.drops = 0

    # -- introspection ----------------------------------------------------

    @property
    def used(self) -> int:
        return self.blocks - len(self._free)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def publish_gauge(self) -> None:
        if obs.REGISTRY.enabled:
            _HOST_USED.set(self.used)

    # -- demote side ------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """One free host row, or None when the tier is full (the caller —
        the radix index — drops its host-LRU leaf and retries)."""
        if not self._free:
            return None
        return self._free.pop()

    def enqueue(self, row: int, device_bid: int) -> None:
        """Stage one demotion: host ``row`` will receive device block
        ``device_bid`` at the next flush. The device block must already
        be in the allocator's ``demoted`` state."""
        assert row not in self.pending, f"host row {row} double-staged"
        self.pending[row] = device_bid
        self.demotions += 1
        if obs.REGISTRY.enabled:
            _DEMOTIONS.inc()
            _HOST_USED.set(self.used)

    def take_pending(self) -> List[Tuple[int, int]]:
        """Drain the staging queue for one flush: ``(host_row,
        device_bid)`` pairs in a stable order. The caller owns the copy
        and the ``free_demoted`` calls; rows stay allocated."""
        items = sorted(self.pending.items())
        self.pending.clear()
        return items

    def commit(
        self,
        rows: List[int],
        k_rows: np.ndarray,
        v_rows: np.ndarray,
        k_scale: Optional[np.ndarray] = None,
        v_scale: Optional[np.ndarray] = None,
    ) -> None:
        """Land one flushed batch: ``k_rows``/``v_rows`` are the gathered
        ``(n, L, Hkv, block, D)`` device arrays for ``rows`` (same
        order), plus the per-block scale scalars under quantized
        serving. This is where the staged D2H fetch actually happens —
        the ONE intended host sync of the tier, positioned off the
        tick's dispatch path by the engine's flush scheduling."""
        idx = np.fromiter(rows, np.int64, len(rows))
        n = len(rows)
        # lint: allow[host-sync] the staged D2H demotion batch lands here — one batched fetch per flush, off the tick
        self.k[idx] = np.asarray(k_rows)[:n]
        # lint: allow[host-sync] second half of the same staged D2H batch
        self.v[idx] = np.asarray(v_rows)[:n]
        if self.quantized:
            # lint: allow[host-sync] per-block K scale scalars of the same batch
            self.k_scale[idx] = np.asarray(k_scale)[:n]
            # lint: allow[host-sync] per-block V scale scalars of the same batch
            self.v_scale[idx] = np.asarray(v_scale)[:n]

    # -- restore side -----------------------------------------------------

    def cancel_pending(self, row: int) -> Optional[int]:
        """If ``row``'s demotion has not flushed yet, cancel it: the
        device block (returned) is still canonical, the host row frees.
        None when the copy already landed (a real restore is needed)."""
        bid = self.pending.pop(row, None)
        if bid is None:
            return None
        self._free.append(row)
        self.restores += 1
        if obs.REGISTRY.enabled:
            _RESTORES.inc()
            _HOST_USED.set(self.used)
        return bid

    def read(self, rows: List[int]) -> Tuple[np.ndarray, ...]:
        """The H2D staging view for a restore batch: stacked
        ``(n, L, Hkv, block, D)`` K and V rows (+ scale scalars when
        quantized), in ``rows`` order. Plain host reads."""
        idx = np.fromiter(rows, np.int64, len(rows))
        out = [self.k[idx], self.v[idx]]
        if self.quantized:
            out += [self.k_scale[idx], self.v_scale[idx]]
        return tuple(out)

    def release(self, row: int, *, restored: bool) -> None:
        """Return one host row after a restore's H2D copy (``restored``)
        or a drop of a flushed node. Pending rows go through
        :meth:`cancel_pending` / :meth:`drop` instead."""
        assert row not in self.pending, (
            f"host row {row} released while still staged"
        )
        self._free.append(row)
        if restored:
            self.restores += 1
            if obs.REGISTRY.enabled:
                _RESTORES.inc()
        else:
            self.drops += 1
        if obs.REGISTRY.enabled:
            _HOST_USED.set(self.used)

    def drop(self, row: int) -> Optional[int]:
        """The host tier's own LRU eviction: the node is leaving the tree
        entirely. Returns the device block id when the demotion was still
        pending (the caller must ``free_demoted`` it — the copy never ran
        and never will), else None (just the host row frees)."""
        bid = self.pending.pop(row, None)
        self._free.append(row)
        self.drops += 1
        if obs.REGISTRY.enabled:
            _HOST_USED.set(self.used)
        return bid

    def stats(self) -> Dict[str, int]:
        return {
            "host_blocks": self.blocks,
            "host_blocks_used": self.used,
            "demotions": self.demotions,
            "restores": self.restores,
            "host_drops": self.drops,
        }
