"""Host-side allocator for the unified paged-KV block pool.

The paged layout (:class:`~tree_attention_tpu.models.decode.PagedKVCache`,
PagedAttention — arXiv:2309.06180) keeps ONE device pool of ``N`` blocks
under every slot AND the radix prefix cache; this module is the host-side
ledger that makes that sharing safe. Ownership is single-writer:

- a **free** block belongs to the allocator's free list;
- a **private** block belongs to exactly one slot (its decode/prefill
  tail — rows only that slot writes);
- a **cached** block belongs to exactly one radix-tree node
  (:class:`~tree_attention_tpu.serving.prefix_cache.PagedPrefixIndex`),
  published there by the slot that prefilled it — ownership moves, the
  bytes do not. Any number of slots may *read* a cached block through
  their tables; the node's pin count (``refs``) tracks them, and the
  tree only evicts refcount-0 leaves.

**Reservation-based admission** is what turns "over-subscribing the pool"
into a clean scheduling decision instead of a shape error deep inside a
jitted gather: an admission reserves its worst-case block count up front
(``ceil((prompt + max_new) / block)`` minus the blocks a prefix hit
already shares) against ``available() = free + evictable - reserved``,
where *evictable* counts cached blocks in fully-unpinned subtrees. If the
reservation does not fit, the request simply WAITS in the queue — the
engine defers admission until retires/evictions free blocks — and a
request that could never fit (needs more than the whole pool) fails
``serve()``'s validation with a clear message. Every later
:meth:`alloc` is backed by a prior reservation, so it cannot fail: when
the free list is empty the evictor (the radix tree's LRU refcount-0-leaf
eviction) is guaranteed to find a victim.

Pure host integers — no device state — so the property tests can hammer
hundreds of random admit/retire/hit/evict interleavings per second.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional

from tree_attention_tpu import obs
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("serving.blocks")

_BLOCKS_USED = obs.gauge(
    "serving_kv_blocks_used",
    "unified KV pool blocks currently owned by a slot or the prefix tree",
)
_BLOCKS_FREE = obs.gauge(
    "serving_kv_blocks_free",
    "unified KV pool blocks on the free list",
)
# Per-shard views of the same ledger (sequence-sharded pool, ISSUE 18):
# the aggregate gauges above keep their unlabeled contract; these expose
# the shard split so /metrics shows placement imbalance directly.
_BLOCKS_USED_SHARD = obs.gauge(
    "serving_kv_blocks_used_shard",
    "KV pool blocks owned per mesh shard (sequence-sharded pool)",
    labels=("shard",),
)
_BLOCKS_FREE_SHARD = obs.gauge(
    "serving_kv_blocks_free_shard",
    "KV pool blocks free per mesh shard (sequence-sharded pool)",
    labels=("shard",),
)

# Block ownership states (the debug ledger's vocabulary). A _DEMOTED
# block is owned by the host tier's staging queue: the radix tree evicted
# it toward host RAM (ISSUE 13), the D2H copy has not run yet, and the
# block must not be reused until the flush lands it on the host and calls
# :meth:`BlockAllocator.free_demoted`. A _SHARED block (ISSUE 15) is a
# copy-on-write fork's full ancestor: refcounted by the slots whose
# tables map it (cached-style shared ownership, but owned by SLOTS, not
# the radix tree), append-only by construction (every owner only writes
# PAST it), freed when the last owner retires.
_FREE, _PRIVATE, _CACHED, _DEMOTED, _SHARED = 0, 1, 2, 3, 4


class BlockAllocator:
    """Free list + reservation accounting over ``blocks`` pool blocks.

    The radix tree registers itself via :meth:`set_evictor`; without one
    (prefix cache off) *evictable* is always 0 and the allocator is a
    plain reserve-then-take free list.
    """

    def __init__(self, blocks: int):
        if blocks < 1:
            raise ValueError(f"block pool needs >= 1 block, got {blocks}")
        self.blocks = blocks
        # Pop from the end -> ascending ids early on (cosmetic, and it
        # makes allocator traces readable).
        self._free: List[int] = list(range(blocks - 1, -1, -1))
        self._state = [_FREE] * blocks  # the double-free/leak ledger
        self.reserved = 0
        # Availability generation: bumped whenever availability can have
        # GROWN (frees, unreserves; the engine also bumps on retire,
        # whose pin releases grow evictability without touching the free
        # list). A deferred admission latches the generation it failed
        # at and skips the O(prompt) re-match + O(tree) evictability
        # recount until the counter moves — pool state can't have
        # improved in between.
        self.gen = 0
        # Lifetime count of blocks handed between slot tables via
        # :meth:`transfer_private` (disaggregation accounting).
        self.transferred = 0
        # Copy-on-write fork accounting (ISSUE 15): per-block owner
        # refcounts of _SHARED blocks, and the lifetime count of
        # share edges taken (each fork_shared bid is one edge).
        self._shared_refs: Dict[int, int] = {}
        self.fork_shares = 0
        self._evict_one: Optional[Callable[[], bool]] = None
        self._evictable: Optional[Callable[[], int]] = None
        # Demotion staging (ISSUE 13): with a host tier under the pool,
        # eviction DEMOTES blocks (state _DEMOTED) instead of freeing
        # them, and the flusher runs the batched D2H gather that finally
        # frees them. ``demote_batch`` is how many leaves one dry alloc
        # demotes before flushing — the batch that makes "one jitted
        # gather per demotion batch" a real amortisation instead of a
        # per-block sync.
        self._flush_demotions: Optional[Callable[[], int]] = None
        self.demote_batch = 8

    # -- the free list (subclass seam) ------------------------------------
    #
    # Every free-list touch goes through these two hooks so a subclass can
    # swap the backing structure (ShardedBlockAllocator keeps one list per
    # mesh shard) without re-deriving any of the ownership transitions or
    # the reservation-soundness argument above.

    def _push_free(self, bid: int) -> None:
        self._free.append(bid)

    def _pop_free(self) -> int:
        return self._free.pop()

    # -- introspection ----------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.blocks - self.free_count

    def evictable(self) -> int:
        return self._evictable() if self._evictable is not None else 0

    def available(self) -> int:
        """Blocks an admission may still reserve: free + evictable-now,
        minus what earlier admissions already promised themselves."""
        return self.free_count + self.evictable() - self.reserved

    def publish_gauges(self) -> None:
        if obs.REGISTRY.enabled:
            _BLOCKS_USED.set(self.used)
            _BLOCKS_FREE.set(self.free_count)

    # -- the evictor hook (the radix tree) --------------------------------

    def set_evictor(
        self, evict_one: Callable[[], bool], evictable: Callable[[], int]
    ) -> None:
        """``evict_one()`` must free one refcount-0 cached leaf into this
        allocator (returning False only when none exists); ``evictable()``
        counts blocks reachable that way."""
        self._evict_one = evict_one
        self._evictable = evictable

    def set_demote_flusher(self, flush: Callable[[], int]) -> None:
        """``flush()`` must complete every pending demotion's D2H copy
        and :meth:`free_demoted` the device blocks, returning how many it
        freed. The engine registers this when KV tiering is on; alloc()
        calls it only when a backed reservation finds the free list dry
        (the common flush point is the engine's end-of-tick staging)."""
        self._flush_demotions = flush

    # -- reservations -----------------------------------------------------

    def reserve(self, n: int) -> bool:
        """Promise ``n`` future :meth:`alloc` calls; False if the pool
        cannot honor them (the engine defers the admission)."""
        if n < 0:
            raise ValueError(f"cannot reserve {n} blocks")
        if n > self.available():
            return False
        self.reserved += n
        return True

    def unreserve(self, n: int) -> None:
        """Return unused reservations (early EOS, retire)."""
        self.reserved -= n
        self.gen += 1
        assert self.reserved >= 0, "block reservation underflow"

    # -- allocation / ownership transitions -------------------------------

    def alloc(self) -> int:
        """One private block, consuming one reservation. Never fails:
        reservations are only granted against free + evictable blocks,
        and pins (which shrink evictability) are themselves reserved."""
        assert self.reserved > 0, "alloc without a backing reservation"
        self.reserved -= 1
        while not self.free_count:
            # Load-bearing calls — NOT inside an assert (python -O strips
            # assert statements, and the eviction must still run). With a
            # host tier, evict_one() DEMOTES (the block parks in state
            # _DEMOTED, not on the free list), so a dry alloc demotes a
            # small batch of leaves and flushes the staged D2H once —
            # one jitted gather per batch, not one sync per block.
            n = 0
            while not self.free_count and n < self.demote_batch:
                if self._evict_one is None or not self._evict_one():
                    break
                n += 1
            if not self.free_count and self._flush_demotions is not None \
                    and self._flush_demotions() > 0:
                continue
            if not self.free_count:
                raise AssertionError(
                    "allocator invariant broken: a backed reservation "
                    "found neither a free block nor an evictable leaf"
                )
        bid = self._pop_free()
        assert self._state[bid] == _FREE, f"block {bid} double-allocated"
        self._state[bid] = _PRIVATE
        return bid

    def publish(self, bid: int) -> None:
        """Ownership transfer private slot -> radix node (zero bytes
        moved — the whole point of the paged layout)."""
        assert self._state[bid] == _PRIVATE, (
            f"block {bid} published while not privately owned"
        )
        self._state[bid] = _CACHED

    def free_private(self, bid: int) -> None:
        """A retiring slot returns a block it still owns."""
        assert self._state[bid] == _PRIVATE, (
            f"block {bid} freed while not privately owned"
        )
        self._state[bid] = _FREE
        self._push_free(bid)
        self.gen += 1

    def unmap_private(self, bid: int) -> None:
        """A slot unmaps a block whose tokens were ROLLED BACK (rejected
        speculation) but keeps its worst-case claim: the block returns to
        the free list AND the reservation it consumed is restored, so the
        slot's later re-allocation cannot fail. Net availability is
        unchanged (+1 free, +1 reserved), hence no generation bump — a
        deferred admission could not be admitted by this."""
        assert self._state[bid] == _PRIVATE, (
            f"block {bid} unmapped while not privately owned"
        )
        self._state[bid] = _FREE
        self._push_free(bid)
        self.reserved += 1

    # -- copy-on-write fork sharing (ISSUE 15) ----------------------------

    @property
    def shared_count(self) -> int:
        """_SHARED blocks currently alive (each counted once, whatever
        its refcount) — a drained engine must read 0 here."""
        return len(self._shared_refs)

    def shared_refs(self, bid: int) -> int:
        """Owner refcount of a shared block (0 when not shared)."""
        return self._shared_refs.get(bid, 0)

    def fork_shared(self, bids: Iterable[int]) -> List[int]:
        """A fork shares full ancestor blocks between parent and child:
        each ``bid`` must be privately owned (first fork — becomes
        ``_SHARED`` with two owners) or already shared (another sibling
        forks the same history — one more owner). The bytes never move
        and never change: shared blocks are full, and every owner only
        appends PAST them, so refcounting is the whole safety story —
        exactly vLLM's copy-on-write fork over PagedAttention block
        tables (arXiv:2309.06180). Returns the bids as the child's
        shared-ownership set; the caller must ledger it (and the
        parent's) so BOTH retires release — the ``ledger-leak`` lint
        pass tracks this acquire site."""
        out: List[int] = []
        for bid in bids:
            if self._state[bid] == _PRIVATE:
                self._state[bid] = _SHARED
                self._shared_refs[bid] = 2
            elif self._state[bid] == _SHARED:
                self._shared_refs[bid] += 1
            else:
                raise AssertionError(
                    f"block {bid} fork-shared while neither private nor "
                    f"shared (state {self._state[bid]}) — sharing a "
                    f"free/cached block would double-own it"
                )
            self.fork_shares += 1
            out.append(bid)
        return out

    def release_shared(self, bid: int) -> None:
        """One owner of a shared block retires. The last release frees
        the block (and grows availability — generation bump); earlier
        ones only drop the refcount."""
        refs = self._shared_refs.get(bid)
        assert refs is not None and self._state[bid] == _SHARED, (
            f"block {bid} shared-released while not shared"
        )
        if refs > 1:
            self._shared_refs[bid] = refs - 1
            return
        del self._shared_refs[bid]
        self._state[bid] = _FREE
        self._push_free(bid)
        self.gen += 1

    def transfer_private(self, bids: Iterable[int]) -> int:
        """Audited ownership handoff of private blocks between slot
        tables (disaggregated serving: a prefill worker's finished slot
        hands its block set to a decode worker, which adopts them into
        its own table — zero KV bytes moved; DistServe, arXiv:2401.09670).

        The ledger state does not change — each block stays ``_PRIVATE``,
        owned by exactly one slot before AND after (the callers move the
        slot-side bookkeeping: table row, private set, and the unspent
        reservation, which stays counted in :attr:`reserved` throughout).
        Net availability is therefore untouched — no generation bump, and
        the reservation-soundness invariant (every future alloc backed by
        free + evictable blocks) holds across the handoff by construction.
        The audit is the point: transferring a block that is *not*
        privately owned (double handoff, a cached block still owned by
        the radix tree, a freed block) is the ownership bug this ledger
        exists to catch, and raises here instead of corrupting the pool.
        Returns the number of blocks transferred."""
        n = 0
        for bid in bids:
            if self._state[bid] != _PRIVATE:
                raise AssertionError(
                    f"block {bid} transferred while not privately owned "
                    f"(state {self._state[bid]}) — handoff of a cached/"
                    f"free block would double-own it"
                )
            n += 1
        self.transferred += n
        return n

    def free_cached(self, bid: int) -> None:
        """The radix tree evicts a refcount-0 leaf's block."""
        assert self._state[bid] == _CACHED, (
            f"block {bid} evicted while not tree-owned"
        )
        self._state[bid] = _FREE
        self._push_free(bid)
        self.gen += 1

    # -- the host tier's transitions (ISSUE 13) ---------------------------

    def demote_cached(self, bid: int) -> None:
        """The radix tree demotes a refcount-0 leaf toward the host tier:
        the block leaves the tree's ownership but is NOT yet free — its
        bytes must survive on the device until the staged D2H gather
        copies them out (``free_demoted``). Not counted available, so the
        reservation-soundness audit holds through the staging window."""
        assert self._state[bid] == _CACHED, (
            f"block {bid} demoted while not tree-owned"
        )
        self._state[bid] = _DEMOTED

    def undemote(self, bid: int) -> None:
        """Cancel a pending demotion: a prefix hit matched the demoted
        node before its D2H copy ran, so the block's device bytes are
        still canonical — hand ownership straight back to the tree (zero
        copies, zero allocations)."""
        assert self._state[bid] == _DEMOTED, (
            f"block {bid} un-demoted while not staged (state "
            f"{self._state[bid]})"
        )
        self._state[bid] = _CACHED

    def free_demoted(self, bid: int) -> None:
        """The staged D2H copy landed on the host: the device block is
        finally reusable."""
        assert self._state[bid] == _DEMOTED, (
            f"block {bid} flushed while not staged for demotion"
        )
        self._state[bid] = _FREE
        self._push_free(bid)
        self.gen += 1


class ShardedBlockAllocator(BlockAllocator):
    """The sequence-sharded pool's ledger (ISSUE 18): ``blocks`` global
    block ids range-partitioned over ``shards`` mesh shards — shard ``s``
    owns ids ``[s*Nl, (s+1)*Nl)`` with ``Nl = blocks // shards``, the SAME
    rule the device pool uses to map a global table entry to a local slice
    row, so the host ledger and the device placement can never disagree.

    One free list per shard; :meth:`alloc` pops from the RICHEST shard so
    a growing slot's blocks interleave across shards and every shard
    carries ~1/W of each slot's keys (balanced flash partials, balanced
    pool pressure). Everything else — ownership states, eviction,
    demotion, CoW sharing — is inherited untouched.

    Reservations stay GLOBAL, which keeps them sound: any block can serve
    any slot through the table indirection (placement only decides which
    pool slice the bytes land in), so ``available()`` over the pooled free
    count is exactly the guarantee :meth:`alloc` needs. Per-shard
    reservations would be strictly weaker bookkeeping for zero safety.
    """

    def __init__(self, blocks: int, shards: int):
        if shards < 1:
            raise ValueError(f"need >= 1 shard, got {shards}")
        if blocks % shards:
            raise ValueError(
                f"pool of {blocks} blocks does not split over {shards} "
                f"shards — round the pool up first"
            )
        self.shards = shards
        self.shard_blocks = blocks // shards
        super().__init__(blocks)
        nl = self.shard_blocks
        self._free_by_shard: List[List[int]] = [
            list(range((s + 1) * nl - 1, s * nl - 1, -1))
            for s in range(shards)
        ]
        self._free = []  # unused; the per-shard lists are the free list

    def shard_of(self, bid: int) -> int:
        return bid // self.shard_blocks

    def _push_free(self, bid: int) -> None:
        self._free_by_shard[bid // self.shard_blocks].append(bid)

    def _pop_free(self) -> int:
        rich = max(
            range(self.shards), key=lambda s: len(self._free_by_shard[s])
        )
        return self._free_by_shard[rich].pop()

    @property
    def free_count(self) -> int:
        return sum(len(f) for f in self._free_by_shard)

    def free_per_shard(self) -> List[int]:
        return [len(f) for f in self._free_by_shard]

    def used_per_shard(self) -> List[int]:
        return [self.shard_blocks - len(f) for f in self._free_by_shard]

    def publish_gauges(self) -> None:
        super().publish_gauges()
        if obs.REGISTRY.enabled:
            for s, nfree in enumerate(self.free_per_shard()):
                _BLOCKS_FREE_SHARD.labels(shard=s).set(nfree)
                _BLOCKS_USED_SHARD.labels(shard=s).set(
                    self.shard_blocks - nfree
                )
