"""``python -m tree_attention_tpu`` — the driver entrypoint.

The reference is run as ``python3 model.py`` (``/root/reference/README.md:13``);
this is that surface, with flags (see :mod:`tree_attention_tpu.cli`).
"""

import sys

from tree_attention_tpu.cli import main

sys.exit(main())
