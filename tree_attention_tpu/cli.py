"""CLI driver: the reference's ``python3 model.py``, grown into a real tool.

The reference entrypoint (``/root/reference/model.py:129-169``) hardcodes one
workload, times one un-fenced call, and prints nothing checkable. Here
``python -m tree_attention_tpu`` with no flags reproduces that workload —
single-query decode over a 64k-token context, 16 heads × 128 — but measured
honestly (fenced, repeated, median) and steered by real flags (SURVEY.md §5):

    python -m tree_attention_tpu                       # reference workload
    python -m tree_attention_tpu --mesh seq=4          # sequence-parallel
    python -m tree_attention_tpu --device cpu --n-virtual-cpu 8 --mesh seq=8
    python -m tree_attention_tpu --mode train --seq-len 2048 --mesh seq=4
    python -m tree_attention_tpu --mode bench --comparator ring ...
    python -m tree_attention_tpu --mode generate --seq-len 128

Modes: ``decode`` (one attention step over a KV cache), ``train`` (LM steps on
the flagship transformer), ``generate`` (prefill + autoregressive decode),
``serve`` (continuous batching: a slot scheduler drains a synthetic request
trace), ``bench`` (the harness; prints one JSON record on stdout).
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Optional

from tree_attention_tpu import obs
from tree_attention_tpu.utils.config import RunConfig, parse_args
from tree_attention_tpu.utils.logging import get_logger, setup_logging

log = get_logger("cli")

# Execution-true host-loop totals (the train/generate loops run eagerly on
# the host; each counted unit is real work the process finished).
_TRAIN_STEPS = obs.counter(
    "train_steps_total", "optimizer steps completed by the CLI train loop"
)
_TRAIN_TOKENS = obs.counter(
    "train_tokens_total", "tokens consumed by completed train steps"
)
_GENERATED_TOKENS = obs.counter(
    "generated_tokens_total", "tokens sampled by the CLI generate mode"
)


def _pick_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _relaunch(cfg: RunConfig, argv: Optional[list]) -> int:
    """``--launch N``: respawn this command as N coordinated processes.

    The multi-host shape (``jax.distributed`` cluster, device pool spanning
    processes) on one machine — the working version of the reference's
    ``mp.spawn`` + hardcoded rendezvous (``model.py:20-21,165``). Uses the
    native fork/exec launcher; ranks and the coordinator address travel by
    environment (see :func:`initialize_distributed
    <tree_attention_tpu.parallel.mesh.initialize_distributed>`).
    """
    from tree_attention_tpu.host_runtime import launch_local

    args = list(sys.argv[1:] if argv is None else argv)
    # Strip --launch so children run the command directly.
    child_args = []
    skip = False
    for a in args:
        if skip:
            skip = False
            continue
        # --metrics-port is parent-only too: N children binding one port
        # would race; the parent keeps the live endpoint.
        parent_only = (
            "--launch", "--launch-timeout", "--heartbeat-stall",
            "--restarts", "--metrics-port",
        )
        if a in parent_only:
            skip = True
            continue
        if a.startswith(tuple(f + "=" for f in parent_only)):
            continue
        child_args.append(a)
    # Elastic restart is only a *resume* if the children restore their
    # latest checkpoint. When the user did NOT pass --resume themselves,
    # the parent adds it and threads the absolute step target, so a
    # restarted child COMPLETES the original --steps budget (an empty
    # --ckpt-dir makes --resume a fresh start, so adding it is safe —
    # though note a --restarts run against a ckpt-dir with prior state
    # declares that state resumable and will continue it). When the user
    # passed --resume explicitly, its documented continuation contract
    # ("run --steps MORE") is kept: each restart attempt then runs --steps
    # from its own restore point, so a crash can extend the total run —
    # bounded, since checkpoints only move forward.
    elastic_resume = bool(
        cfg.restarts and cfg.ckpt_dir and "--resume" not in child_args
    )
    if elastic_resume:
        child_args.append("--resume")
    elif cfg.restarts and cfg.ckpt_dir:
        log.warning(
            "--restarts with explicit --resume keeps continuation "
            "semantics: each restart runs --steps more from its restore "
            "point rather than completing one fixed budget"
        )
    cmd = [sys.executable, "-m", "tree_attention_tpu", *child_args]
    log.info("launching %d coordinated processes: %s", cfg.launch, cmd)
    # The coordinator address travels to the children via inherited env;
    # restore the parent's env afterwards so a later in-process run doesn't
    # find a stale coordinator.
    prev = {
        k: os.environ.get(k) for k in ("TA_COORDINATOR", "TA_TRAIN_TOTAL_STEPS")
    }
    os.environ["TA_COORDINATOR"] = f"localhost:{_pick_free_port()}"
    if elastic_resume and cfg.mode == "train":
        # A restarted child must COMPLETE the original budget, not run
        # --steps more from its restored point (_run_train reads this).
        os.environ["TA_TRAIN_TOTAL_STEPS"] = str(cfg.steps)
    try:
        failures, statuses = launch_local(
            cmd, cfg.launch, timeout=cfg.launch_timeout,
            heartbeat_stall=cfg.heartbeat_stall, restarts=cfg.restarts,
        )
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if cfg.restarts and not failures:
        from tree_attention_tpu.host_runtime import last_launch_attempts

        attempts = last_launch_attempts()
        if attempts > 1:
            log.warning("launch: recovered after %d attempt(s)", attempts)
    if failures:
        log.error("launch: %d/%d ranks failed: %s", failures, cfg.launch,
                  statuses)
    return 1 if failures else 0


def _emit(record: dict) -> None:
    """Print the run's one JSON record — from process 0 only."""
    import jax

    if jax.process_index() == 0:
        print(json.dumps(record))


def _configure_backend(cfg: RunConfig) -> None:
    """Pick the platform before any JAX backend initialises.

    Must run before the first device query. ``--n-virtual-cpu`` implies the
    CPU platform (the virtual-device flag only affects the CPU client). The
    config API is used as well as the env var because TPU plugins (e.g. the
    axon platform) can override ``JAX_PLATFORMS`` from the environment.
    """
    device = cfg.device
    if cfg.n_virtual_cpu > 0:
        device = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{cfg.n_virtual_cpu}"
            ).strip()
    import jax

    if device != "auto":
        jax.config.update("jax_platforms", device)


def _build_mesh(cfg: RunConfig):
    from tree_attention_tpu.parallel.mesh import make_mesh

    axes = cfg.mesh_axes()
    if axes is None:
        return None
    return make_mesh(axes)


def _dtype(cfg: RunConfig):
    import jax.numpy as jnp

    return jnp.dtype(cfg.dtype)


def _run_decode(cfg: RunConfig, mesh) -> int:
    """The reference workload: one decode step, timed; parity with
    ``main()`` at ``/root/reference/model.py:129-155``."""
    import jax

    from tree_attention_tpu.bench.harness import bench_decode

    res = bench_decode(cfg, mesh)
    log.info(
        "decode: %d KV tokens, %d heads x %d, %s, %d device(s)",
        cfg.seq_len, cfg.heads, cfg.head_dim, cfg.dtype,
        1 if mesh is None else mesh.size,
    )
    log.info(
        "median %.4fs per step  (%.0f KV tokens/s, %.2e FLOP/s)",
        res.timing.median, res.tokens_per_sec, res.flops_per_sec,
    )
    if res.peak_hbm_bytes:
        log.info("peak HBM: %.1f MiB", res.peak_hbm_bytes / 2**20)
    _emit(res.as_dict())
    return 0


def _run_bench(cfg: RunConfig, mesh) -> int:
    from tree_attention_tpu.bench.harness import run_bench

    record = run_bench(cfg, mesh)
    _emit(record)
    return 0


def _transformer_config(cfg: RunConfig):
    import jax.numpy as jnp

    from tree_attention_tpu.models import TransformerConfig

    d_head = cfg.model_dim // cfg.heads
    return TransformerConfig(
        vocab_size=cfg.vocab_size,
        d_model=cfg.model_dim,
        n_layers=cfg.n_layers,
        n_heads=cfg.heads,
        n_kv_heads=cfg.resolved_kv_heads(),
        d_head=d_head,
        d_ff=int(8 * cfg.model_dim / 3 + 127) // 128 * 128,
        max_seq_len=max(cfg.seq_len, 128),
        dtype=_dtype(cfg),
        attn_impl=cfg.impl,
        attn_block_size=cfg.block_size,
        seq_layout=cfg.seq_layout,
    )


def _run_train(cfg: RunConfig, mesh) -> int:
    """LM training steps on the flagship model (the capability the reference
    lacks entirely — no loss, no backward, no optimizer)."""
    import jax

    import jax.numpy as jnp

    from tree_attention_tpu.data import make_lm_batch
    from tree_attention_tpu.models import (
        count_params, default_optimizer, init_train_state, make_train_step,
        shard_batch,
    )
    from tree_attention_tpu.utils.profiling import time_fn

    if cfg.steps < 1:
        # Throughput timing below reuses the last training batch; with no
        # steps there is neither a batch nor anything meaningful to time.
        raise SystemExit("train mode requires --steps >= 1")
    tcfg = _transformer_config(cfg)
    opt = default_optimizer()
    state = init_train_state(jax.random.PRNGKey(cfg.seed), tcfg, opt, mesh=mesh)
    # Donation reuses the old state's buffers — unsafe while an async
    # checkpoint save may still be reading them, so it's off when saving.
    step = make_train_step(tcfg, opt, mesh=mesh, donate=not cfg.ckpt_dir)
    log.info(
        "transformer: %d params, %d layers, d_model %d, seq %d",
        count_params(state[0]), tcfg.n_layers, tcfg.d_model, cfg.seq_len,
    )
    if cfg.resume and not cfg.ckpt_dir:
        raise SystemExit("--resume requires --ckpt-dir")
    ckpt = start_step = None
    if cfg.ckpt_dir:
        import contextlib

        from tree_attention_tpu.checkpoint import Checkpointer, load_model_config

        ckpt = Checkpointer(cfg.ckpt_dir, save_interval_steps=cfg.ckpt_every)
        if cfg.resume and ckpt.latest_step() is not None:
            with contextlib.suppress(FileNotFoundError):
                saved_cfg = load_model_config(cfg.ckpt_dir)
                if saved_cfg != tcfg:
                    raise SystemExit(
                        f"checkpoint config in {cfg.ckpt_dir} disagrees with "
                        f"the CLI flags:\n  saved: {saved_cfg}\n  flags: {tcfg}"
                    )
            state, start_step = ckpt.restore(state)
            log.info("resumed from step %d", start_step)
    start = 0 if start_step is None else start_step + 1
    # Plain --resume keeps its documented continuation semantics: run
    # --steps MORE steps from the restored point. An elastic restart
    # (--launch --restarts) instead completes the ORIGINAL budget — a
    # restart is a resume, not a redo — so the parent threads the absolute
    # target through the environment alongside the rank protocol.
    end = start + cfg.steps
    total = os.environ.get("TA_TRAIN_TOTAL_STEPS")
    if total is not None:
        end = max(int(total), start)
    key = jax.random.PRNGKey(cfg.seed + 1)
    pipe = None
    corpus = None
    if cfg.data:
        from tree_attention_tpu.host_runtime import (
            HostCorpusPipeline, TokenCorpus, native_available,
        )

        # Real data: mmap'd token corpus, same resume contract as the
        # synthetic pipeline (batch k is a pure function of (seed, k)).
        corpus = TokenCorpus(cfg.data, dtype=cfg.data_dtype)
        pipe = HostCorpusPipeline(
            corpus, cfg.batch, cfg.seq_len, cfg.seed + 1, start=start,
        )
        log.info(
            "corpus pipeline: %s (%d tokens, native=%s)",
            cfg.data, len(corpus), native_available(),
        )
    elif cfg.host_data:
        from tree_attention_tpu.host_runtime import HostDataPipeline, native_available

        # Batch content is a pure function of (seed, step index), so resume
        # starts the pipeline at `start` — no replayed training data.
        pipe = HostDataPipeline(
            (cfg.batch, cfg.seq_len + 1), tcfg.vocab_size, cfg.seed + 1,
            start=start,
        )
        log.info("host data pipeline (native=%s)", native_available())

    def next_batch(i):
        if pipe is None:
            return make_lm_batch(
                jax.random.fold_in(key, i), batch=cfg.batch,
                seq_len=cfg.seq_len, vocab_size=tcfg.vocab_size, mesh=mesh,
            )
        toks = pipe.next()  # numpy; slice as host views, one transfer each
        if corpus is not None:
            # XLA's gather clamps out-of-range ids, which would silently
            # train on garbage; fail loudly instead. Cheap: a host max over
            # one batch.
            hi = int(toks.max())
            if hi >= tcfg.vocab_size:
                raise SystemExit(
                    f"corpus token id {hi} >= --vocab-size "
                    f"{tcfg.vocab_size} (step {i}); retokenize or raise "
                    f"--vocab-size"
                )
        b = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        if mesh is not None:
            return shard_batch(mesh, b)
        return {k: jnp.asarray(v) for k, v in b.items()}

    losses = []
    saved_last = True
    try:
        from tree_attention_tpu.host_runtime import heartbeat, maybe_inject_fault

        for i in range(start, end):
            maybe_inject_fault(i)  # env-armed test crash (supervision/elastic)
            batch = next_batch(i)
            state, loss = step(state, batch)
            losses.append(float(loss))
            heartbeat()  # after the fetch: real per-step progress, not dispatch
            _TRAIN_STEPS.inc()
            _TRAIN_TOKENS.inc(cfg.batch * cfg.seq_len)
            log.info("step %d: loss %.4f", i, losses[-1])
            if ckpt is not None:
                saved_last = ckpt.save(i, state, cfg=tcfg)
        if ckpt is not None and not saved_last and end > start:
            # The save interval skipped the final step; the resumable state
            # must include all completed work.
            ckpt.save(end - 1, state, cfg=tcfg, force=True)
        if end == start:
            # Restarted after the budget was already complete: nothing to
            # train this attempt (losses stays empty), but the record still
            # needs a batch to time the compiled step against — fetched
            # here, while the data pipeline/corpus are still open.
            batch = next_batch(start)
    finally:
        if pipe is not None:
            pipe.close()
        if corpus is not None:
            corpus.close()
        if ckpt is not None:
            ckpt.close()
    # Throughput of the compiled step (last batch, post-compile). Timing
    # re-runs with the same state, so a donating step can't be reused —
    # with --ckpt-dir the step is already non-donating.
    step_t = step if cfg.ckpt_dir else make_train_step(
        tcfg, opt, mesh=mesh, donate=False
    )
    stats = time_fn(step_t, state, batch, iters=max(cfg.iters, 1), warmup=1)
    toks = cfg.batch * cfg.seq_len
    log.info(
        "train step: median %.4fs (%.0f tokens/s)",
        stats.median, toks / stats.median,
    )
    _emit({
        "mode": "train",
        "losses": losses,
        "tokens_per_sec": round(toks / stats.median, 1),
        **stats.as_dict(),
    })
    return 0


def _run_generate(cfg: RunConfig, mesh) -> int:
    import jax

    from tree_attention_tpu.models import generate, init_params

    if cfg.temperature < 0:
        raise SystemExit("--temperature must be >= 0 (0 = greedy)")
    if cfg.max_new_tokens < 1:
        raise SystemExit("--max-new-tokens must be >= 1")
    if cfg.kv_quant != "none" and cfg.impl not in ("auto", "pallas_decode"):
        # Same rejection the bench surface gives this flag pair.
        raise SystemExit(
            f"--kv-quant {cfg.kv_quant} runs a pallas_decode q8 kernel; "
            f"--impl {cfg.impl} cannot serve a quantized buffer"
        )
    tcfg = _transformer_config(cfg)
    params = init_params(jax.random.PRNGKey(cfg.seed), tcfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(cfg.seed + 1), (cfg.batch, max(cfg.q_len, 1)),
        0, tcfg.vocab_size,
    )
    from tree_attention_tpu.host_runtime import heartbeat

    n_new = cfg.max_new_tokens
    # Generation is one dispatch: progress granularity is the whole call,
    # so a watchdog stall window must cover it.
    heartbeat()
    toks = generate(
        params, prompt, n_new, tcfg,
        temperature=cfg.temperature, key=jax.random.PRNGKey(cfg.seed + 2),
        mesh=mesh,
        quantize_after_prefill=cfg.kv_quant != "none",
        quant_kernel=cfg.resolved_quant_kernel() or "q8q",
    )
    toks = jax.block_until_ready(toks)
    heartbeat()
    _GENERATED_TOKENS.inc(cfg.batch * n_new)
    log.info(
        "generated %s tokens from a %s prompt%s",
        toks.shape, prompt.shape,
        f" ({cfg.kv_quant} KV cache)" if cfg.kv_quant != "none" else "",
    )
    _emit({
        "mode": "generate",
        "tokens": toks.tolist(),
        **({"kv_quant": cfg.kv_quant} if cfg.kv_quant != "none" else {}),
    })
    return 0


def _run_serve(cfg: RunConfig, mesh) -> int:
    """Continuous batching over a synthetic request trace: the slot
    scheduler admits/retires requests while one compiled ragged decode step
    serves every live slot per tick (``tree_attention_tpu/serving``)."""
    import jax

    from tree_attention_tpu.models import init_params
    from tree_attention_tpu.serving import SlotServer, synthetic_trace

    if cfg.max_new_tokens < 1:
        raise SystemExit("--max-new-tokens must be >= 1")
    if cfg.slots < 1:
        raise SystemExit("--slots must be >= 1")
    if cfg.prompt_len - cfg.prompt_jitter < 1:
        raise SystemExit("--prompt-jitter must leave prompts >= 1 token")
    if cfg.prefill_chunk < 1:
        raise SystemExit("--prefill-chunk must be >= 1")
    if cfg.prefill_budget is not None and cfg.prefill_budget < 1:
        raise SystemExit("--prefill-budget must be >= 1")
    if cfg.kv_quant != "none" and cfg.impl not in ("auto", "pallas_decode"):
        raise SystemExit(
            f"--kv-quant {cfg.kv_quant} runs a pallas_decode q8 kernel; "
            f"--impl {cfg.impl} cannot serve a quantized buffer"
        )
    if cfg.max_queue < 1:
        raise SystemExit("--max-queue must be >= 1")
    if cfg.serve_fleet and cfg.serve_http is not None:
        raise SystemExit(
            "--serve-fleet and --serve-http are exclusive: the router "
            "IS the fleet's HTTP front door (it listens on --router-port)"
        )
    if cfg.serve_fleet and cfg.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    if cfg.serve_disagg:
        if cfg.serve_fleet:
            raise SystemExit(
                "--serve-disagg and --serve-fleet are exclusive (a "
                "disaggregated fleet tier is not built yet; run one "
                "disaggregated pair per process)"
            )
        if cfg.kv_layout != "paged":
            raise SystemExit(
                "--serve-disagg requires --kv-layout paged: the zero-"
                "copy handoff IS paged-block ownership transfer"
            )
        if cfg.admission != "chunked":
            raise SystemExit(
                "--serve-disagg requires --admission chunked (the "
                "prefill pool is a chunked-prefill worker)"
            )
        if cfg.prefill_slots < 1:
            raise SystemExit("--prefill-slots must be >= 1")
        decode_slots = (cfg.decode_slots if cfg.decode_slots is not None
                        else cfg.slots - cfg.prefill_slots)
        if decode_slots < 1:
            raise SystemExit(
                f"--serve-disagg needs >= 1 decode slot: --slots "
                f"{cfg.slots} minus --prefill-slots {cfg.prefill_slots} "
                f"leaves {decode_slots} (pass --decode-slots or raise "
                f"--slots)"
            )
    if cfg.default_deadline is not None and cfg.default_deadline <= 0:
        raise SystemExit("--default-deadline must be > 0 seconds")
    # --speculate composes with sampling (ISSUE 20): temperature > 0
    # runs the stochastic (Leviathan) accept walk, which emits the
    # target distribution exactly — no greedy restriction.
    if cfg.top_k < 0:
        raise SystemExit("--top-k must be >= 0 (0 = off)")
    if cfg.temperature < 0:
        raise SystemExit("--temperature must be >= 0 (0 = greedy)")
    if cfg.speculate and not 1 <= cfg.draft_k <= 31:
        raise SystemExit("--draft-k must be in [1, 31]")
    if not 0.0 <= cfg.prefix_share <= 1.0:
        raise SystemExit("--prefix-share must be in [0, 1]")
    if cfg.prefix_cache and (cfg.prefix_block < 1
                             or cfg.prefix_block & (cfg.prefix_block - 1)):
        raise SystemExit("--prefix-block must be a power of two >= 1")
    if cfg.host_blocks < 0:
        raise SystemExit("--host-blocks must be >= 0")
    host_blocks = cfg.host_blocks if cfg.kv_tiering == "on" else 0
    if host_blocks:
        if cfg.kv_layout != "paged":
            raise SystemExit(
                "--host-blocks KV tiering requires --kv-layout paged "
                "(the tier demotes pool blocks; the contiguous layout "
                "has none)"
            )
        if not cfg.prefix_cache:
            raise SystemExit(
                "--host-blocks KV tiering requires --prefix-cache "
                "(demotion is what radix eviction becomes; with no "
                "radix tree nothing ever demotes)"
            )
    if cfg.kv_shard == "seq" and cfg.kv_layout != "paged":
        raise SystemExit(
            "--kv-shard seq requires --kv-layout paged (sequence "
            "sharding partitions the block pool; the contiguous layout "
            "has none)"
        )
    if cfg.kv_block is not None and (cfg.kv_block < 1
                                     or cfg.kv_block & (cfg.kv_block - 1)):
        raise SystemExit("--kv-block must be a power of two >= 1")
    if cfg.kv_blocks is not None and cfg.kv_blocks < 1:
        raise SystemExit("--kv-blocks must be >= 1")
    if cfg.kv_layout == "paged" and cfg.prefix_cache \
            and cfg.kv_block is not None and cfg.kv_block != cfg.prefix_block:
        # The engine enforces this too (radix matching happens at page
        # granularity); surface it as the clean flag-error every other
        # serve-mode misuse gets, not a traceback.
        raise SystemExit(
            f"--prefix-block {cfg.prefix_block} must equal --kv-block "
            f"{cfg.kv_block} under --kv-layout paged (or pass only one "
            f"of them)"
        )
    if cfg.kv_layout == "contiguous" and (cfg.kv_block is not None
                                          or cfg.kv_blocks is not None):
        log.warning(
            "--kv-block/--kv-blocks only apply to --kv-layout paged; "
            "the contiguous layout allocates slots * cache_len and a "
            "separate prefix pool (the flags are ignored)"
        )
    # The cache is sized from the trace itself: longest possible prompt
    # plus the per-request budget, through the same rounding rule
    # generate() uses.
    from tree_attention_tpu.models.decode import round_cache_len

    cache_len = round_cache_len(
        cfg.prompt_len + cfg.prompt_jitter + cfg.max_new_tokens, mesh
    )
    if cfg.prefix_cache and cfg.prefix_block > cache_len:
        # Same clean rejection every sibling flag misuse gets — the
        # engine would raise the equivalent ValueError as a traceback.
        raise SystemExit(
            f"--prefix-block {cfg.prefix_block} exceeds the trace's slot "
            f"capacity {cache_len} (prompt-len + jitter + max-new-tokens, "
            f"rounded)"
        )
    import dataclasses as _dc

    tcfg = _transformer_config(_dc.replace(cfg, seq_len=cache_len))
    params = init_params(jax.random.PRNGKey(cfg.seed), tcfg)
    if cfg.slo_ttft <= 0 or cfg.slo_tbt <= 0:
        raise SystemExit("--slo-ttft and --slo-tbt must be > 0")
    # The paged layout has ONE device budget (--kv-blocks) and one host
    # budget (--host-blocks); the PR-6-deprecated --prefix-pool-blocks
    # alias is gone (ISSUE 13) — the engine API keeps the retention-cap
    # kwarg for tests, but the CLI no longer exposes the old split.
    kv_blocks = cfg.kv_blocks
    drafter = cfg.drafter
    if cfg.speculate and cfg.drafter == "model":
        # A shrunk draft transformer (half the layers, same vocab) from
        # its own seed — the two-model speculative shape, CPU-proxy
        # sized. Acceptance depends on how well it tracks the big model;
        # the free 'ngram' drafter is the default for a reason.
        from tree_attention_tpu.serving.speculation import (
            DraftModelDrafter,
        )

        draft_cfg = _dc.replace(
            tcfg, n_layers=max(tcfg.n_layers // 2, 1)
        )
        drafter = DraftModelDrafter(
            init_params(jax.random.PRNGKey(cfg.seed + 3), draft_cfg),
            draft_cfg,
        )
    engine_kw = dict(
        slots=cfg.slots, cache_len=cache_len, mesh=mesh,
        quantize=cfg.kv_quant != "none",
        quant_kernel=cfg.resolved_quant_kernel() or "q8q",
        temperature=cfg.temperature, top_k=cfg.top_k, seed=cfg.seed + 2,
        prefill_chunk=cfg.prefill_chunk,
        prefill_budget=cfg.prefill_budget,
        admission=cfg.admission,
        slo_ttft=cfg.slo_ttft,
        slo_tbt=cfg.slo_tbt,
        prefix_cache=cfg.prefix_cache,
        prefix_block=cfg.prefix_block,
        kv_layout=cfg.kv_layout,
        kv_block=cfg.kv_block,
        kv_blocks=kv_blocks,
        kv_shard=cfg.kv_shard,
        host_blocks=host_blocks,
        speculate=cfg.speculate,
        draft_k=cfg.draft_k,
        drafter=drafter,
    )

    def make_engine():
        if cfg.serve_disagg:
            # The disaggregated pair (ISSUE 12): same seams as a fused
            # SlotServer, so the ingress below works unchanged on top.
            from tree_attention_tpu.serving.disagg import DisaggServer

            disagg_kw = {k: v for k, v in engine_kw.items()
                         if k not in ("slots", "admission", "kv_layout")}
            return DisaggServer(
                params, tcfg, prefill_slots=cfg.prefill_slots,
                decode_slots=decode_slots, **disagg_kw,
            )
        return SlotServer(params, tcfg, **engine_kw)

    from tree_attention_tpu.host_runtime import heartbeat

    if cfg.serve_fleet:
        # The fleet tier (ISSUE 11): --replicas in-process engines, each
        # behind its own loopback ingress, fronted by the cache-aware
        # router — one process, N engines (the CPU-proxy honest shape;
        # ProcessReplica + FleetSupervisor serve the multi-host story).
        from tree_attention_tpu.serving.fleet import (
            FleetSupervisor,
            LocalReplica,
            install_fleet_drain_signals,
        )
        from tree_attention_tpu.serving.router import FleetRouter

        if not cfg.prefix_cache:
            log.warning(
                "--serve-fleet without --prefix-cache: affinity routing "
                "groups shared prefixes per replica, but no replica can "
                "reuse them — expect no TTFT win"
            )
        reps = [
            LocalReplica(
                f"r{i}", make_engine,
                max_queue=cfg.max_queue,
                default_deadline_s=cfg.default_deadline,
                default_max_tokens=cfg.max_new_tokens,
            )
            for i in range(cfg.replicas)
        ]
        router = FleetRouter(
            port=cfg.router_port,
            block=cfg.prefix_block,
            affinity=cfg.affinity == "on",
        )
        fleet = FleetSupervisor(reps, router=router)
        drained = install_fleet_drain_signals(fleet)
        port = fleet.start()
        log.info(
            "serving fleet on http://127.0.0.1:%d/v1/completions "
            "(%d replica(s) x %d slot(s), cache_len %d, affinity %s) — "
            "SIGTERM rolls the fleet down gracefully",
            port, cfg.replicas, cfg.slots, cache_len, cfg.affinity,
        )
        heartbeat()
        drained.wait()  # blocks until SIGTERM/SIGINT
        fleet.stop()
        heartbeat()
        _emit({
            "mode": "serve",
            "fleet": {
                "router_port": port,
                "replicas": cfg.replicas,
                "affinity": cfg.affinity,
                "router": router.stats(),
                "leaks": fleet.leak_reports(),
            },
            "slots": cfg.slots,
            "cache_len": cache_len,
            "kv_layout": cfg.kv_layout,
        })
        return 0

    server = make_engine()
    if _METRICS_HTTP["server"] is not None:
        # /slots introspection (ISSUE 16): the exporter started before
        # the engine existed; wire it now.
        _METRICS_HTTP["server"].attach_engine(server)

    if cfg.serve_http is not None:
        # The live ingress (ISSUE 10): serve real HTTP traffic until a
        # drain signal (SIGTERM/SIGINT) winds the engine down; no
        # synthetic trace — the slot capacity is still sized from
        # --prompt-len/--prompt-jitter/--max-new-tokens.
        from tree_attention_tpu.serving.ingress import (
            IngressServer, install_drain_signals,
        )

        ingress = IngressServer(
            server,
            port=cfg.serve_http,
            max_queue=cfg.max_queue,
            default_deadline_s=cfg.default_deadline,
            default_max_tokens=cfg.max_new_tokens,
        )
        install_drain_signals(ingress)
        port = ingress.start()
        log.info(
            "serving HTTP on http://127.0.0.1:%d/v1/completions "
            "(%d slot(s), cache_len %d, max queue %d%s) — SIGTERM "
            "drains gracefully",
            port, cfg.slots, cache_len, cfg.max_queue,
            f", default deadline {cfg.default_deadline}s"
            if cfg.default_deadline is not None else "",
        )
        heartbeat()
        report = ingress.join()  # blocks until drained
        ingress.stop()
        heartbeat()
        if report is None:
            # The engine thread died instead of draining — a crash must
            # not masquerade as a clean exit.
            log.error("engine loop crashed: %r", ingress.engine_error)
            return 1
        _emit({
            "mode": "serve",
            "ingress": {"port": port, "max_queue": cfg.max_queue,
                        "default_deadline_s": cfg.default_deadline},
            "slots": cfg.slots,
            "cache_len": cache_len,
            "kv_layout": cfg.kv_layout,
            **({"disagg": {"prefill_slots": cfg.prefill_slots,
                           "decode_slots": decode_slots}}
               if cfg.serve_disagg else {}),
            **(report.as_dict() if report is not None else {}),
        })
        return 0

    trace = synthetic_trace(
        cfg.requests,
        prompt_len=cfg.prompt_len,
        prompt_jitter=cfg.prompt_jitter,
        max_new_tokens=cfg.max_new_tokens,
        arrival_every=cfg.arrival_every,
        vocab_size=tcfg.vocab_size,
        seed=cfg.seed + 1,
        prefix_share=cfg.prefix_share,
        prefix_len=cfg.prefix_len,
    )
    heartbeat()
    report = server.serve(trace)
    heartbeat()
    log.info(
        "served %d requests on %d slot(s): %.1f tokens/s aggregate, "
        "mean occupancy %.2f",
        len(report.results), cfg.slots, report.tokens_per_sec,
        report.mean_occupancy,
    )
    _emit({
        "mode": "serve",
        "slots": cfg.slots,
        "cache_len": cache_len,
        "admission": cfg.admission,
        "prefill_chunk": cfg.prefill_chunk,
        "kv_layout": cfg.kv_layout,
        **({"disagg": {"prefill_slots": cfg.prefill_slots,
                       "decode_slots": decode_slots}}
           if cfg.serve_disagg else {}),
        **({"speculate": {"draft_k": cfg.draft_k, "drafter": cfg.drafter}}
           if cfg.speculate else {}),
        **({"prefix_cache": {
            "block": cfg.prefix_block,
        }} if cfg.prefix_cache else {}),
        **({"kv_tiering": {"host_blocks": host_blocks}}
           if host_blocks else {}),
        # Outcome counts ride ServeReport.as_dict (the ISSUE 10 outcome
        # vocabulary threaded through the report).
        **report.as_dict(),
        **({"kv_quant": cfg.kv_quant} if cfg.kv_quant != "none" else {}),
    })
    return 0


#: The live telemetry exporter, when --metrics-port started one — the
#: seam _run_serve uses to late-wire the engine behind /slots (the
#: exporter starts before the engine exists).
_METRICS_HTTP: dict = {"server": None}


def _start_metrics_http(cfg: RunConfig):
    """Start the live telemetry endpoint, or return None without the flag.

    /metrics needs the registry recording, /healthz + /flight need the
    ring armed, and /requests needs the request ledger armed even when no
    exit sinks were asked for (memory-only rings serve all three).
    """
    if cfg.metrics_port is None:
        return None
    obs.REGISTRY.enable()
    if not obs.FLIGHT.enabled:
        obs.FLIGHT.arm()
    if not obs.REQLOG.enabled:
        obs.REQLOG.arm()
    from tree_attention_tpu.obs.http import MetricsHTTPServer

    server = MetricsHTTPServer(cfg.metrics_port)
    port = server.start()
    _METRICS_HTTP["server"] = server
    log.info(
        "telemetry endpoint: http://127.0.0.1:%d/metrics "
        "(/metrics.json /healthz /flight /requests /slots)", port,
    )
    return server


def main(argv: Optional[list] = None) -> int:
    cfg = parse_args(argv)
    # Under --launch, every child would otherwise open (and rotate) the same
    # file, corrupting each other's sink — rank-suffix the children's path.
    log_file = cfg.log_file
    if log_file and os.environ.get("TA_COORDINATOR"):
        log_file = f"{log_file}.p{os.environ.get('JAX_PROCESS_INDEX', '0')}"
    setup_logging(
        getattr(logging, cfg.log_level.upper()),
        log_file=log_file,
        all_processes=cfg.all_processes,
    )
    http_server = None
    try:
        if cfg.launch > 1:
            # The parent records launcher metrics; children re-run main()
            # with the same flags and rank-suffix their own sinks.
            obs.configure(
                metrics_out=cfg.metrics_out, trace_events=cfg.trace_events
            )
            # The parent serves the live endpoint (--metrics-port is
            # stripped from children): its launcher/heartbeat metrics are
            # the multi-process run's live view.
            http_server = _start_metrics_http(cfg)
            obs.install_crash_handlers()
            return _relaunch(cfg, argv)
        _configure_backend(cfg)

        import jax

        from tree_attention_tpu.parallel.mesh import initialize_distributed
        from tree_attention_tpu.utils.profiling import trace

        initialize_distributed()
        # Telemetry arms AFTER distributed init so the tracer's pid and the
        # metrics path's rank suffix see the real process index — on
        # auto-detected multi-host runs neither TA_COORDINATOR nor
        # JAX_PROCESS_INDEX exists in the environment.
        obs.configure(
            metrics_out=cfg.metrics_out, trace_events=cfg.trace_events,
            flight_out=cfg.flight_out,
        )
        http_server = _start_metrics_http(cfg)
        if (obs.REGISTRY.enabled or obs.TRACER.active
                or obs.FLIGHT.enabled):
            # An interrupted run still flushes its sinks (atexit +
            # SIGTERM; SIGUSR1 dumps the flight ring and keeps running).
            obs.install_crash_handlers()
        log.info(
            "backend=%s devices=%d mesh=%s mode=%s",
            jax.default_backend(), jax.device_count(), cfg.mesh or "none",
            cfg.mode,
        )
        mesh = _build_mesh(cfg)
        runner = {
            "decode": _run_decode,
            "train": _run_train,
            "generate": _run_generate,
            "serve": _run_serve,
            "bench": _run_bench,
        }[cfg.mode]
        with trace(cfg.profile_dir), obs.span(
            f"mode:{cfg.mode}",
            args=None if not obs.TRACER.active else {"mesh": cfg.mesh},
        ):
            return runner(cfg, mesh)
    finally:
        if http_server is not None:
            http_server.stop()
        sinks = obs.shutdown()
        if sinks["metrics_out"] or sinks["trace_events"] \
                or sinks["flight_out"]:
            # The exit snapshot contract of --metrics-out /
            # --trace-events / --flight-out.
            log.info(
                "telemetry: metrics=%s trace=%s flight=%s",
                sinks["metrics_out"] or "-", sinks["trace_events"] or "-",
                sinks["flight_out"] or "-",
            )


if __name__ == "__main__":
    sys.exit(main())
