"""Checkpoint / resume: sharded train-state persistence via Orbax.

The reference has nothing to checkpoint — no model, no optimizer, no resume
(SURVEY.md §5 records the absence). This framework has a real train state
(:data:`tree_attention_tpu.models.train.TrainState` — params + optax state),
so it gets the subsystem the reference never needed, built TPU-native:

- Orbax ``CheckpointManager`` with async save and retention (``max_to_keep``);
- **sharding-preserving restore**: each host reads exactly its own shards of
  a ``NamedSharding``-placed state (no host ever materialises the full
  pytree), and the restored arrays land with the same mesh placement they
  were saved with — resume composes with ``make_train_step``'s donation;
- a JSON sidecar for the model config, so a checkpoint directory is
  self-describing.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

import orbax.checkpoint as ocp

from tree_attention_tpu.models.transformer import TransformerConfig
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("checkpoint")

_CONFIG_FILE = "model_config.json"


def _abstract_like(tree: Any) -> Any:
    """ShapeDtypeStructs (with shardings where present) describing ``tree``.

    Accepts a concrete state or one already made of ShapeDtypeStructs.
    """

    def leaf(x):
        sharding = getattr(x, "sharding", None)
        if isinstance(x, (int, float, np.ndarray)) or not hasattr(x, "shape"):
            x = np.asarray(x)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree.map(leaf, tree)


def save_model_config(directory: str, cfg: TransformerConfig) -> None:
    """Write the architecture sidecar (dtype stored by name)."""
    d = dataclasses.asdict(cfg)
    d["dtype"] = np.dtype(cfg.dtype).name
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, _CONFIG_FILE)
    if jax.process_index() == 0:
        with open(path, "w") as f:
            json.dump(d, f, indent=2)


def load_model_config(directory: str) -> TransformerConfig:
    import jax.numpy as jnp

    with open(os.path.join(directory, _CONFIG_FILE)) as f:
        d = json.load(f)
    d["dtype"] = jnp.dtype(d["dtype"])
    return TransformerConfig(**d)


class Checkpointer:
    """Step-indexed checkpoint manager for a (params, opt_state) train state.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(step, state)                       # async; fenced on exit
        state, step = ckpt.restore(state_template)   # sharded, latest step
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
    ):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def save(
        self, step: int, state: Any, *, cfg: Optional[TransformerConfig] = None,
        force: bool = False,
    ) -> bool:
        """Queue an async save of ``state`` at ``step``; returns whether a
        save was started (the manager skips off-interval steps)."""
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved and cfg is not None and not os.path.exists(
            os.path.join(self.directory, _CONFIG_FILE)
        ):
            save_model_config(self.directory, cfg)
        if saved:
            log.info("checkpoint queued: step %d -> %s", step, self.directory)
        return saved

    def restore(
        self, state_template: Any, step: Optional[int] = None
    ) -> Tuple[Any, int]:
        """Restore ``(state, step)``; ``state_template`` supplies shapes,
        dtypes and shardings (a concrete state or ShapeDtypeStruct tree)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}"
                )
        abstract = _abstract_like(state_template)
        state = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        log.info("checkpoint restored: step %d from %s", step, self.directory)
        return state, step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
