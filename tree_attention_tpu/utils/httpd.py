"""Shared stdlib HTTP plumbing: one daemon-thread server, two front-ends.

PR 4's metrics exporter (:mod:`~tree_attention_tpu.obs.http`) proved the
pattern this repo wants from an HTTP surface — stdlib
:class:`~http.server.ThreadingHTTPServer` (zero new dependencies), bound
to localhost unless explicitly exposed, served from a daemon thread that
dies with the process, ``port=0`` letting the OS pick for tests and
parallel runs.  The serving ingress (ISSUE 10) needs the identical
lifecycle; hand-rolling a second copy would fork the bind/teardown
semantics the tests pin.  This module is that plumbing, factored once:

- :class:`DaemonHTTPServer` — bind/start/stop/port lifecycle plus the
  length-framed :meth:`reply` helper.  Subclasses implement
  :meth:`handle` (method + parsed path routing); anything they raise
  from a vanished client (``BrokenPipeError`` / ``ConnectionResetError``)
  is swallowed here, once.
- Handlers run on per-connection daemon threads
  (``daemon_threads = True``), so a slow or stuck client can never block
  :meth:`stop` or process exit — the property the ingress's slow-reader
  chaos arm leans on.

Streaming responses (the ingress's SSE token feed) bypass :meth:`reply`
and write the handler's ``wfile`` directly; the server stays HTTP/1.0
(close-delimited bodies), so a stream simply ends when the handler
returns and the connection closes.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DaemonHTTPServer:
    """Daemon-thread HTTP server lifecycle over a subclass :meth:`handle`.

    Bind: localhost by default (none of this repo's HTTP surfaces are
    open services); pass ``host="0.0.0.0"`` explicitly to expose one.
    ``port=0`` lets the OS pick — :attr:`port` reports the bound port
    after :meth:`start`.
    """

    #: Thread name for the accept loop (subclasses override for ps/py-spy
    #: readability).
    thread_name = "httpd"

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._host = host
        self._want_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port.
        Idempotent — a second call returns the existing port."""
        if self._httpd is not None:
            return self.port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr per request
                pass

            def _dispatch(self, method: str) -> None:
                try:
                    server.handle(method, self)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-reply

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    @property
    def port(self) -> int:
        return 0 if self._httpd is None else self._httpd.server_address[1]

    @property
    def running(self) -> bool:
        return self._httpd is not None

    # -- routing (subclass hook) ------------------------------------------

    def handle(self, method: str, req: BaseHTTPRequestHandler) -> None:
        """Route one request; the default is a 404 for everything."""
        self.reply(req, 404, f"no such endpoint: {req.path}\n", "text/plain")

    # -- reply helper ------------------------------------------------------

    @staticmethod
    def reply(req: BaseHTTPRequestHandler, code: int, body: str,
              ctype: str, headers: Optional[dict] = None) -> None:
        """One complete, length-framed response."""
        data = body.encode("utf-8")
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            req.send_header(k, str(v))
        req.end_headers()
        req.wfile.write(data)
