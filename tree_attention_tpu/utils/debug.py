"""Numerics & determinism debugging: the sanitizer story, JAX-style.

The reference has no sanitizers, race detection, or numeric checks of any
kind (SURVEY.md §5) — three bugs shipped in 169 lines partly because nothing
ever checked an output. SPMD-by-construction designs away classic data races,
so what remains worth checking on TPU is:

- **NaN/Inf escape** from kernels (``checkify`` functional error checks that
  survive ``jit``; :func:`checked` / :func:`assert_finite`);
- **cross-shard divergence**: an array that should be replicated across a
  mesh axis silently differing per shard — the SPMD analogue of a data race,
  typically caused by nondeterministic collectives or shard-dependent control
  flow (:func:`assert_replicated_identical`);
- **cross-run nondeterminism** for an op that should be bitwise reproducible
  (:func:`assert_deterministic`).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

import numpy as np
from jax.experimental import checkify

from tree_attention_tpu.utils.logging import get_logger

log = get_logger("debug")


def checked(
    fn: Callable[..., Any], *, errors=checkify.float_checks, jit: bool = True
) -> Callable[..., Any]:
    """Wrap ``fn`` with ``checkify`` float checks; raises on NaN/Inf/div0.

    The checkified body is jitted *inside* the wrapper and the error is
    raised outside the jit boundary (``check_error`` cannot run under a
    trace — do not wrap the result in another ``jax.jit``). Use in tests
    and debug runs; the unchecked path has zero overhead because nothing
    is wrapped there.
    """
    cfn = checkify.checkify(fn, errors=errors)
    if jit:
        cfn = jax.jit(cfn)

    def wrapper(*args, **kwargs):
        err, out = cfn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper


def assert_finite(tree: Any, name: str = "value") -> None:
    """Eager NaN/Inf check over a pytree (host-side; fetches values)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if not np.isfinite(arr).all():
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            raise FloatingPointError(
                f"{name}{jax.tree_util.keystr(path)}: {n_nan} NaN, "
                f"{n_inf} Inf of {arr.size} elements"
            )


def assert_replicated_identical(
    x: jax.Array, *, name: str = "array", atol: float = 0.0
) -> None:
    """Check a nominally-replicated array is identical on every device shard.

    The SPMD divergence detector: after a ``shard_map`` whose out_spec says
    "replicated", every addressable shard must hold the same bytes. A
    mismatch means shard-dependent computation leaked into a replicated
    output (the moral equivalent of a data race in the reference's NCCL
    world). ``atol=0`` demands bitwise equality — TPU collectives are
    deterministic, so that's the honest default.
    """
    shards = x.addressable_shards
    if len(shards) < 2:
        return
    ref = np.asarray(shards[0].data)
    for s in shards[1:]:
        got = np.asarray(s.data)
        if atol == 0.0:
            ok = np.array_equal(ref, got, equal_nan=True)
        else:
            ok = np.allclose(ref, got, atol=atol, equal_nan=True)
        if not ok:
            diff = np.abs(ref.astype(np.float64) - got.astype(np.float64))
            raise AssertionError(
                f"{name}: replicated shards diverge — device "
                f"{s.device} differs from {shards[0].device} "
                f"(max abs diff {diff.max():.3e})"
            )


def assert_deterministic(
    fn: Callable[..., Any], *args: Any, runs: int = 2, name: Optional[str] = None,
    **kwargs: Any,
) -> Any:
    """Run ``fn`` ``runs`` times; raise if any output bit differs.

    Catches nondeterministic reductions/scatter orders in a kernel under
    test. Returns the (verified) first output.
    """
    first = jax.block_until_ready(fn(*args, **kwargs))
    label = name or getattr(fn, "__name__", "fn")
    for r in range(1, runs):
        again = jax.block_until_ready(fn(*args, **kwargs))
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(first)[0],
            jax.tree_util.tree_flatten_with_path(again)[0],
        ):
            if not np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True):
                raise AssertionError(
                    f"{label}{jax.tree_util.keystr(pa)}: run {r} differs "
                    f"from run 0 — nondeterministic computation"
                )
    return first
