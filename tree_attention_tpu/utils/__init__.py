"""Cross-cutting utilities: logging, config/flags, profiling & timing.

The reference's cross-cutting layer is loguru sprinkled through every function
plus a rotating file sink (``/root/reference/model.py:160``) and a hardcoded
problem size (``model.py:140-145``) with no flag system at all (SURVEY.md §5).
Here those become four real modules:

- :mod:`.logging`   — structured stdlib logging, per-process prefixes,
  process-0-only default, optional rotating file sink.
- :mod:`.config`    — one dataclass config + argparse bridge; defaults
  reproduce the reference's hardcoded run.
- :mod:`.profiling` — fenced timing (``block_until_ready``), device memory
  stats (peak HBM), and ``jax.profiler`` trace capture.
- :mod:`.debug`     — checkify/NaN checks, SPMD shard-divergence and
  determinism assertions (the sanitizer story the reference lacks).
"""

from tree_attention_tpu.utils.config import (  # noqa: F401
    RunConfig,
    build_arg_parser,
    parse_args,
    parse_mesh_spec,
)
from tree_attention_tpu.utils.debug import (  # noqa: F401
    assert_deterministic,
    assert_finite,
    assert_replicated_identical,
    checked,
)
from tree_attention_tpu.utils.logging import (  # noqa: F401
    get_logger,
    setup_logging,
)
from tree_attention_tpu.utils.profiling import (  # noqa: F401
    DEFLATION_MIN_CYCLES,
    DEFLATION_RATIO,
    SlopeStats,
    TimingStats,
    chain_slope,
    deflation_suspect,
    device_memory_stats,
    slope_per_step,
    time_fn,
    time_per_step,
    trace,
)
