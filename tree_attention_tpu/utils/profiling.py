"""Profiling & timing: honest numbers on an async dispatch runtime.

The reference's entire observability story is one ``time.time()`` pair around
a single call (``/root/reference/model.py:149-153``) — which on an async
runtime like JAX would time the *dispatch*, not the work. Here every timing
fences with ``jax.block_until_ready`` and reports robust statistics, device
memory stats expose peak HBM, and ``trace`` wraps ``jax.profiler`` capture
(TensorBoard/Perfetto) as SURVEY.md §5 mandates.
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax


@dataclasses.dataclass
class TimingStats:
    """Per-call wall-clock stats over ``iters`` fenced repetitions, seconds."""

    median: float
    mean: float
    minimum: float
    maximum: float
    iters: int
    times: Sequence[float]

    def tokens_per_sec(self, tokens: int) -> float:
        return tokens / self.median

    def as_dict(self) -> Dict[str, Any]:
        return {
            "median_s": self.median,
            "mean_s": self.mean,
            "min_s": self.minimum,
            "max_s": self.maximum,
            "iters": self.iters,
        }


def time_fn(
    fn: Callable[..., Any],
    *args: Any,
    iters: int = 10,
    warmup: int = 2,
    **kwargs: Any,
) -> TimingStats:
    """Time ``fn(*args, **kwargs)`` with compile warmup and result fencing."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    for _ in range(max(warmup, 0)):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return TimingStats(
        median=statistics.median(times),
        mean=statistics.fmean(times),
        minimum=min(times),
        maximum=max(times),
        iters=iters,
        times=tuple(times),
    )


def device_memory_stats(device: Optional[jax.Device] = None) -> Optional[Dict[str, int]]:
    """Allocator stats for one device (peak HBM lives in ``peak_bytes_in_use``).

    Returns None on backends without memory stats (e.g. CPU).
    """
    if device is None:
        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items() if isinstance(v, (int, float))}


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler`` trace capture; no-op when ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
