"""Profiling & timing: honest numbers on an async dispatch runtime.

The reference's entire observability story is one ``time.time()`` pair around
a single call (``/root/reference/model.py:149-153``) — which on an async
runtime like JAX would time the *dispatch*, not the work. Here every timing
fences with ``jax.block_until_ready`` and reports robust statistics, device
memory stats expose peak HBM, and ``trace`` wraps ``jax.profiler`` capture
(TensorBoard/Perfetto) as SURVEY.md §5 mandates.
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np

from tree_attention_tpu import obs

# The measurement-hygiene guards (physical ceiling, deflation screen,
# jitter note) file their verdicts here as well as into the records they
# annotate, so a round's runs can be audited for guard-trip rates without
# re-parsing every record (ISSUE 1: deflation/ceiling verdicts as
# structured events).
_GUARD_VERDICTS = obs.counter(
    "timing_guard_verdicts_total",
    "measurement-hygiene guard verdicts by guard kind",
    labels=("record", "guard"),
)


def record_guard_verdict(
    record: str, guard: str, reason: Optional[str] = None
) -> None:
    """Count one guard verdict and mirror it as a trace instant.

    ``guard`` taxonomy (one physical fault can legitimately file under the
    side the guard actually computed — the label says WHICH screen fired):

    - ``ceiling`` — a derived rate (implied bandwidth, MFU) exceeds the
      hardware spec: the fence did not fence (bench.py's slope records);
    - ``floor`` — a wall-clock reading sits below the physical minimum
      time for the workload (bench_decode's median check, tune_sweep's
      per-cycle screen) — the time-domain dual of ``ceiling``;
    - ``deflation`` — min cycle far below its siblings' median: the
      transport resolved a fetch early;
    - ``jitter`` — wide spread / median≫min: contended window, estimate
      stands but is an upper bound;
    - ``clean`` — every screen that ran passed (``reason`` names any
      screen the call site could not run, e.g. jitter needs >= 3 repeats).
    """
    if obs.REGISTRY.enabled:
        _GUARD_VERDICTS.labels(record=record, guard=guard).inc()
    if obs.TRACER.active:
        # Each instrument under its own guard: a tracer-only run used to
        # lose every guard_verdict event to the registry early-return.
        args = {"record": record, "guard": guard}
        if reason:
            args["reason"] = reason
        obs.instant("guard_verdict", cat="timing", args=args)


@dataclasses.dataclass
class TimingStats:
    """Per-call wall-clock stats over ``iters`` fenced repetitions, seconds."""

    median: float
    mean: float
    minimum: float
    maximum: float
    iters: int
    times: Sequence[float]

    def tokens_per_sec(self, tokens: int) -> float:
        return tokens / self.median

    def as_dict(self) -> Dict[str, Any]:
        return {
            "median_s": self.median,
            "mean_s": self.mean,
            "min_s": self.minimum,
            "max_s": self.maximum,
            "iters": self.iters,
        }


def time_fn(
    fn: Callable[..., Any],
    *args: Any,
    iters: int = 10,
    warmup: int = 2,
    fetch: bool = False,
    **kwargs: Any,
) -> TimingStats:
    """Time ``fn(*args, **kwargs)`` with compile warmup and result fencing.

    ``fetch=True`` fences by copying every output to host instead of
    ``block_until_ready`` — required on transports where readiness
    notifications resolve before execution finishes (observed on tunneled
    TPU backends); it adds the device→host transfer to the measured time,
    so pair it with :func:`time_per_step` slope timing to cancel fixed
    overhead.
    """
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")

    def fence(res):
        if fetch:
            jax.tree.map(np.asarray, res)
        else:
            jax.block_until_ready(res)

    from tree_attention_tpu.host_runtime import heartbeat

    # One span per time_fn call (never per iteration — the timed loop must
    # not carry telemetry), trace-sinked only when a sink is armed.
    with obs.span("time_fn", cat="timing",
                  args=None if not obs.TRACER.active else
                  {"iters": iters, "warmup": warmup, "fetch": fetch}):
        for _ in range(max(warmup, 0)):
            fence(fn(*args, **kwargs))
            heartbeat()  # each fenced iteration is host-visible progress
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fence(fn(*args, **kwargs))
            times.append(time.perf_counter() - t0)
            heartbeat()
    return TimingStats(
        median=statistics.median(times),
        mean=statistics.fmean(times),
        minimum=min(times),
        maximum=max(times),
        iters=iters,
        times=tuple(times),
    )


@dataclasses.dataclass
class SlopeStats:
    """Per-step slope estimate over ``repeats`` independent measurement
    cycles (each cycle: min-of-``iters`` small chain, min-of-``iters`` large
    chain, slope of the difference).

    ``per_step`` is the minimum over positive cycle slopes — tunnel RPC
    noise is additive and heavy-tailed, so a cycle whose window hit host
    contention only ever *inflates* its slope, and the min converges to the
    true cost. ``spread_pct`` ((max−min)/min over the positive slopes) is
    the run's recorded variance: a large spread says some cycles were noisy
    and the min is doing real work (VERDICT r4 weak item 1 — the official
    capture must carry its own error bar).
    """

    per_step: float
    slopes: Tuple[float, ...]
    spread_pct: float
    small: TimingStats
    large: TimingStats


DEFLATION_MIN_CYCLES = 3
DEFLATION_RATIO = 0.6


def deflation_suspect(slope: "SlopeStats") -> Optional[str]:
    """Reason string when the min cycle looks DEFLATED, else None.

    The additive-noise model behind the min-stat estimator (contention
    only ever inflates a cycle) failed on 2026-08-01: in a bad transport
    window the tunnel resolved fetches before the chained program had
    finished, producing cycle slopes up to ~2x too FAST — some below the
    physical roofline (caught by the bandwidth/MFU ceiling guards), some
    not (a 16k fwd sweep cell read 194 TFLOP/s on a 197-peak chip). A
    deflated cycle shows up as the min sitting far below the median of
    its siblings (< ``DEFLATION_RATIO`` x); genuine contention (e.g. the
    r5 q8q capture's [359, 359, 497] us) keeps min ~= median.

    Needs at least ``DEFLATION_MIN_CYCLES`` positive cycles: with two,
    median == mean and the test would flag one ordinarily-contended
    cycle at >2.33x as a deflated min. Callers that want this defence
    must run ``repeats >= 3``.

    Known bound of the defence: a fault window long enough to deflate
    MOST cycles by a similar factor keeps min ~= median and passes this
    screen — by construction no intra-run statistic can separate that
    from a genuinely clean capture. The remaining nets for that case are
    the physical-ceiling guards (a whole-window deflation large enough
    to matter usually crosses the bandwidth/MFU spec, as the 2026-08-01
    sweep cells did) and cross-capture comparison: records publish their
    ``slope_cycles_us`` + commit + timestamp precisely so a later reader
    can diff same-shape captures across runs.
    """
    positive = [s for s in slope.slopes if s > 0]
    if len(positive) < len(slope.slopes):
        # A non-positive cycle is hard evidence of a faulty window on its
        # own — a chain cannot cost nothing — regardless of how many
        # clean-looking siblings survive: the surviving min is data from
        # the same window that produced the nonsense cycles. "Could not
        # check" must not read as "checked and clean". (Flagging costs
        # only a re-run.)
        return (
            f"only {len(positive)} of {len(slope.slopes)} cycle slopes "
            "positive: the non-positive cycles signal a faulty transport "
            "window; discard this record"
        )
    if len(positive) >= DEFLATION_MIN_CYCLES:
        med = statistics.median(positive)
        if slope.per_step < DEFLATION_RATIO * med:
            return (
                f"min cycle {slope.per_step * 1e6:.0f} us is "
                f"<{DEFLATION_RATIO}x the median cycle {med * 1e6:.0f} us: "
                "transport deflation fault suspected (fetch resolved "
                "early); discard this record"
            )
    return None


def slope_per_step(
    make_fn: Callable[[int], Callable[..., Any]],
    *args: Any,
    n_small: int = 64,
    n_large: int = 256,
    iters: int = 5,
    warmup: int = 1,
    fetch: bool = True,
    stat: str = "median",
    repeats: int = 1,
    **kwargs: Any,
) -> SlopeStats:
    """Amortised per-step cost by slope: time an ``n_small``-step and an
    ``n_large``-step chained program and divide the difference.

    Cancels every fixed cost — dispatch, RPC latency, the host fetch used as
    the completion fence — leaving only the marginal cost of one step.
    ``make_fn(n)`` must return a callable running ``n`` dependent steps.

    ``stat`` picks the per-side estimator: ``"median"`` (default) or
    ``"min"``. Tunnel RPC noise is strictly additive and heavy-tailed
    (observed multi-hundred-ms spikes on an idle host), so the minimum over
    ``iters`` repetitions converges to the true time and is the right choice
    on the tunneled TPU backend; the median is kept as the default for
    backends where run-to-run variance is symmetric.

    ``repeats`` runs the whole (small, large) cycle that many times on the
    SAME compiled programs (no recompiles after the first) and takes the
    minimum positive slope — the defence against a single contended
    measurement window inflating both sides' minima together, which one
    cycle cannot detect (observed: the r4 driver capture read the 64k decode
    33 points below the same commit's earlier run). The per-cycle slopes and
    their spread come back in :class:`SlopeStats` so records can publish
    their variance.

    Protocol note: have the chain return a small *reduction* of its output
    (e.g. ``out.sum()``), not the full tensor — the fence fetches the result
    to host, and a multi-MB fetch adds seconds of jittery RPC per call that
    the slope then has to cancel.
    """
    if not 0 < n_small < n_large:
        raise ValueError(f"need 0 < n_small < n_large, got {n_small}, {n_large}")
    if stat not in ("median", "min"):
        raise ValueError(f"stat must be 'median' or 'min', got {stat!r}")
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    fn_small = make_fn(n_small)
    fn_large = make_fn(n_large)
    pick = (lambda s: s.minimum) if stat == "min" else (lambda s: s.median)
    slopes = []
    s_small = s_large = None
    for cycle in range(repeats):
        # Warmup (the compile) only on the first cycle; later cycles reuse
        # the executables, so extra warmup runs would just spend the
        # machine's time without changing the estimator.
        w = warmup if cycle == 0 else 0
        with obs.span("slope_cycle", cat="timing",
                      args=None if not obs.TRACER.active else
                      {"cycle": cycle, "n_small": n_small,
                       "n_large": n_large}):
            s_small = time_fn(
                fn_small, *args, iters=iters, warmup=w, fetch=fetch, **kwargs
            )
            s_large = time_fn(
                fn_large, *args, iters=iters, warmup=w, fetch=fetch, **kwargs
            )
        slopes.append((pick(s_large) - pick(s_small)) / (n_large - n_small))
    positive = [s for s in slopes if s > 0]
    if not positive:
        raise RuntimeError(
            f"non-positive per-step slope in every cycle ({slopes}): {stat}s "
            f"at n={n_small}/{n_large} — measurement noise exceeds the "
            f"workload; raise n_large or iters"
        )
    spread = (max(positive) - min(positive)) / min(positive) * 100
    return SlopeStats(
        per_step=min(positive),
        slopes=tuple(slopes),
        spread_pct=spread,
        small=s_small,
        large=s_large,
    )


def chain_slope(
    step: Callable[..., Any],
    carry: Any,
    *rest: Any,
    n_small: int,
    n_large: int,
    iters: int = 5,
    warmup: int = 1,
    stat: str = "min",
    repeats: int = 3,
) -> SlopeStats:
    """Slope-time ``step`` via an on-device dependent chain.

    The one blessed harness for per-step kernel timing on the tunneled
    transport, used by every live caller (bench.py's decode/q8/train
    records and the tile A/B; ``tools/experiments_r4.py`` keeps its own
    copy because it is the frozen round-4 measurement script, kept
    exactly as its recorded artifacts ran): ``step(carry, *rest) ->
    next_carry`` is chained
    ``n`` times under ``lax.scan`` (each step consumes the previous
    output, so nothing can overlap or be elided), the chain returns a
    SCALAR reduction of the final carry (a full-tensor fetch costs
    seconds of heavy-tailed RPC per call that the slope would then have
    to cancel), and the (small, large) chain pair goes through
    :func:`slope_per_step`'s min-stat repeated-cycle protocol. Callers
    that need gradients or multi-output steps fold them into the carry
    themselves — XLA dead-code-eliminates any output that does not feed
    the carry chain.
    """
    import jax.numpy as jnp
    from jax import lax

    def mk(n):
        def f(c, *r):
            def body(cc, _):
                return step(cc, *r).astype(cc.dtype), None

            out = lax.scan(body, c, None, length=n)[0]
            return jnp.sum(out.astype(jnp.float32))

        return jax.jit(f)

    return slope_per_step(
        mk, carry, *rest, n_small=n_small, n_large=n_large,
        iters=iters, warmup=warmup, stat=stat, repeats=repeats,
    )


def time_per_step(
    make_fn: Callable[[int], Callable[..., Any]],
    *args: Any,
    **kwargs: Any,
) -> Tuple[float, TimingStats, TimingStats]:
    """Single-cycle form of :func:`slope_per_step` (kept for callers that
    unpack the original 3-tuple); same parameters and semantics."""
    s = slope_per_step(make_fn, *args, **kwargs)
    return s.per_step, s.small, s.large


def device_memory_stats(device: Optional[jax.Device] = None) -> Optional[Dict[str, int]]:
    """Allocator stats for one device (peak HBM lives in ``peak_bytes_in_use``).

    Returns None on backends without memory stats (e.g. CPU).
    """
    if device is None:
        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items() if isinstance(v, (int, float))}


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler`` trace capture; no-op when ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
