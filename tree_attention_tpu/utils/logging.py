"""Structured logging: the reference's loguru layer, done as a real subsystem.

The reference calls ``logger.info/debug`` at every layer and adds a file sink
with 10 MB rotation (``/root/reference/model.py:160``) — but never declares
loguru as a dependency (``requirements.txt:1-3``) and logs identically from
every rank. Here:

- stdlib ``logging`` only (no undeclared deps);
- every record carries a ``[pK/N]`` process prefix (multi-host JAX runs one
  process per host, so this is the host rank);
- by default only process 0 logs at the configured level; other processes are
  clamped to WARNING (pass ``all_processes=True`` for per-host debug);
- optional rotating file sink mirroring the reference's 10 MB rotation.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
from typing import Optional

_ROOT_NAME = "tree_attention_tpu"
_FORMAT = "%(asctime)s %(levelname).1s %(process_prefix)s %(name)s: %(message)s"


class _ProcessPrefixFilter(logging.Filter):
    """Stamps each record with the JAX process index and enforces the
    process-0-only level clamp **per record**, so the rank decision is made
    with whatever information exists at emit time — before distributed init
    every host looks like rank 0 (fail-open), afterwards non-zero hosts are
    clamped to WARNING without any re-setup call."""

    def __init__(self, clamp_nonzero: bool):
        super().__init__()
        self.clamp_nonzero = clamp_nonzero

    def filter(self, record: logging.LogRecord) -> bool:
        idx = _process_index()
        record.process_prefix = f"[p{idx}]"
        if self.clamp_nonzero and idx != 0 and record.levelno < logging.WARNING:
            return False
        return True


def _process_index() -> int:
    """Best-effort host rank. A JAX *distributed* runtime is authoritative;
    otherwise an explicitly exported ``JAX_PROCESS_INDEX`` wins (JAX never
    sets it — a launcher that wants rank-aware logging exports it, as
    :func:`tree_attention_tpu.host_runtime.launch_local` does; without it a
    launcher-spawned child would see itself as an independent rank-0 world).
    With neither, assume rank 0 — fail-open: too much logging beats silently
    losing a host's warnings."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None and _distributed_initialized(jax_mod):
        try:
            return jax_mod.process_index()
        except Exception:
            pass
    env = os.environ.get("JAX_PROCESS_INDEX")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass  # malformed export: fall through to the rank-0 default
    if jax_mod is not None and _backend_initialized():
        try:
            return jax_mod.process_index()
        except Exception:
            pass
    return 0


def _process_count() -> int:
    """Best-effort world size, with the same probing discipline (and the
    same fail-open rank-0/world-1 default) as :func:`_process_index`."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None and _distributed_initialized(jax_mod):
        try:
            return jax_mod.process_count()
        except Exception:
            pass
    env = os.environ.get("TA_NUM_PROCESSES")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    if jax_mod is not None and _backend_initialized():
        try:
            return jax_mod.process_count()
        except Exception:
            pass
    return 1


def _distributed_initialized(jax_mod) -> bool:
    try:
        return jax_mod.distributed.is_initialized()
    except Exception:
        return False


def _backend_initialized() -> bool:
    """True iff a JAX backend has already been created. ``jax.process_index``
    *initialises* the backend as a side effect — logging must never do that
    (it would lock the platform before the CLI's ``--device``/virtual-device
    flags are applied)."""
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return xla_bridge.backends_are_initialized()
        return bool(xla_bridge._backends)
    except Exception:
        return False


def get_logger(name: str = _ROOT_NAME) -> logging.Logger:
    """Namespaced logger; children of the package root inherit its handlers."""
    if name != _ROOT_NAME and not name.startswith(_ROOT_NAME + "."):
        name = f"{_ROOT_NAME}.{name}"
    return logging.getLogger(name)


def setup_logging(
    level: int = logging.INFO,
    *,
    log_file: Optional[str] = None,
    rotate_mb: int = 10,
    all_processes: bool = False,
    stream=None,
) -> logging.Logger:
    """Configure the package root logger. Idempotent (replaces handlers).

    Args:
      level: threshold for process 0 (and everyone, if ``all_processes``).
      log_file: optional path for a rotating file sink (the reference's
        ``logger.add(..., rotation="10 MB")`` equivalent).
      rotate_mb: file size per rotation segment, in MB.
      all_processes: log from every process at ``level`` instead of clamping
        non-zero processes to WARNING.
      stream: stream for the console handler (defaults to stderr).
    """
    root = logging.getLogger(_ROOT_NAME)
    for h in list(root.handlers):
        root.removeHandler(h)
        h.close()

    root.setLevel(level)
    root.propagate = False

    # The rank clamp lives in the per-record filter (not a one-shot level
    # computation) so it holds on hosts whose rank is only known after
    # jax.distributed initialises — setup_logging typically runs before that.
    flt = _ProcessPrefixFilter(clamp_nonzero=not all_processes)
    fmt = logging.Formatter(_FORMAT)
    console = logging.StreamHandler(stream if stream is not None else sys.stderr)
    console.setFormatter(fmt)
    console.addFilter(flt)
    root.addHandler(console)

    if log_file:
        fileh = logging.handlers.RotatingFileHandler(
            log_file, maxBytes=rotate_mb * 1024 * 1024, backupCount=3
        )
        fileh.setFormatter(fmt)
        fileh.addFilter(flt)
        root.addHandler(fileh)

    return root
