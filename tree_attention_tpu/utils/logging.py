"""Structured logging: the reference's loguru layer, done as a real subsystem.

The reference calls ``logger.info/debug`` at every layer and adds a file sink
with 10 MB rotation (``/root/reference/model.py:160``) — but never declares
loguru as a dependency (``requirements.txt:1-3``) and logs identically from
every rank. Here:

- stdlib ``logging`` only (no undeclared deps);
- every record carries a ``[pK/N]`` process prefix (multi-host JAX runs one
  process per host, so this is the host rank);
- by default only process 0 logs at the configured level; other processes are
  clamped to WARNING (pass ``all_processes=True`` for per-host debug);
- optional rotating file sink mirroring the reference's 10 MB rotation.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
from typing import Optional

_ROOT_NAME = "tree_attention_tpu"
_FORMAT = "%(asctime)s %(levelname).1s %(process_prefix)s %(name)s: %(message)s"


class _ProcessPrefixFilter(logging.Filter):
    """Stamps each record with the JAX process index without forcing JAX to
    initialise at import time (``jax.process_index()`` would start the
    backend; env inspection keeps logging usable before/without devices)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.process_prefix = f"[p{_process_index()}]"
        return True


def _process_index() -> int:
    """Best-effort host rank. JAX (if imported) is authoritative; the
    ``JAX_PROCESS_INDEX`` env var is an *explicit launcher-set override* for
    logging before the backend initialises (JAX itself never sets it — a
    multi-host launcher that wants pre-init rank-aware logging exports it,
    as ``native/launcher`` does). With neither, assume rank 0 — fail-open:
    too much logging beats silently losing a host's warnings."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            return jax_mod.process_index()
        except Exception:
            pass
    return int(os.environ.get("JAX_PROCESS_INDEX", "0"))


def get_logger(name: str = _ROOT_NAME) -> logging.Logger:
    """Namespaced logger; children of the package root inherit its handlers."""
    if name != _ROOT_NAME and not name.startswith(_ROOT_NAME + "."):
        name = f"{_ROOT_NAME}.{name}"
    return logging.getLogger(name)


def setup_logging(
    level: int = logging.INFO,
    *,
    log_file: Optional[str] = None,
    rotate_mb: int = 10,
    all_processes: bool = False,
    stream=None,
) -> logging.Logger:
    """Configure the package root logger. Idempotent (replaces handlers).

    Args:
      level: threshold for process 0 (and everyone, if ``all_processes``).
      log_file: optional path for a rotating file sink (the reference's
        ``logger.add(..., rotation="10 MB")`` equivalent).
      rotate_mb: file size per rotation segment, in MB.
      all_processes: log from every process at ``level`` instead of clamping
        non-zero processes to WARNING.
      stream: stream for the console handler (defaults to stderr).
    """
    root = logging.getLogger(_ROOT_NAME)
    for h in list(root.handlers):
        root.removeHandler(h)
        h.close()

    effective = level if (all_processes or _process_index() == 0) else max(
        level, logging.WARNING
    )
    root.setLevel(effective)
    root.propagate = False

    fmt = logging.Formatter(_FORMAT)
    console = logging.StreamHandler(stream if stream is not None else sys.stderr)
    console.setFormatter(fmt)
    console.addFilter(_ProcessPrefixFilter())
    root.addHandler(console)

    if log_file:
        fileh = logging.handlers.RotatingFileHandler(
            log_file, maxBytes=rotate_mb * 1024 * 1024, backupCount=3
        )
        fileh.setFormatter(fmt)
        fileh.addFilter(_ProcessPrefixFilter())
        root.addHandler(fileh)

    return root
