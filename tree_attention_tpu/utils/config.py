"""Config/flag system: one dataclass, one argparse bridge.

The reference has no config system at all — problem size is hardcoded at
``/root/reference/model.py:140-145``, rendezvous at ``model.py:20-21``, dtype
and seed inside ``make_data`` (``model.py:50-53``). SURVEY.md §5 mandates a
dataclass + flags whose **defaults reproduce the reference run**:
seq_len=64000, 16 heads, head_dim=128, B=1, q_len=1 decode.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Dict, Optional, Sequence


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse ``"seq=8"`` / ``"data=2,seq=2,model=2"`` into an ordered axis map.

    A size of -1 absorbs remaining devices (see ``mesh.make_mesh``).
    """
    axes: Dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(f"bad mesh axis {part!r}; want name=size")
        name, _, size = part.partition("=")
        name = name.strip()
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r}")
        axes[name] = int(size)
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


@dataclasses.dataclass
class RunConfig:
    """Everything the driver needs; field defaults == the reference workload."""

    # Problem size (reference: model.py:140-145, 51-53).
    batch: int = 1
    seq_len: int = 64000
    q_len: int = 1
    heads: int = 16
    kv_heads: Optional[int] = None  # None → MHA (kv_heads == heads)
    head_dim: int = 128
    causal: bool = False
    dtype: str = "bfloat16"  # TPU-native half; reference used fp16 on CPU

    # Execution.
    mode: str = "decode"  # decode | train | generate | bench | serve
    device: str = "auto"  # auto | tpu | cpu
    mesh: Optional[str] = None  # e.g. "seq=8" or "data=2,seq=2,model=2"
    n_virtual_cpu: int = 0  # >0: force N virtual CPU devices (tests/emulation)
    launch: int = 0  # >1: respawn N coordinated processes (multi-host shape)
    launch_timeout: Optional[float] = None  # seconds; kill all ranks at expiry
    heartbeat_stall: Optional[float] = None  # seconds; hang watchdog window
    restarts: int = 0  # elastic: whole-gang relaunches after a failure
    impl: str = "auto"  # auto | naive | blockwise | pallas | pallas_decode
    block_size: Optional[int] = None  # None -> impl-appropriate default
    kv_quant: str = "none"  # none | int8 (int8-MXU q8q) | int8-cast (bf16-cast q8)
    seq_layout: str = "contiguous"  # contiguous | zigzag (train mode, seq>1)
    seed: int = 0

    # Timing / bench.
    iters: int = 10
    warmup: int = 2
    comparator: str = "none"  # none | ring (train shape) | ring-decode (bench mode)

    # Training mode.
    steps: int = 3
    model_dim: int = 256
    n_layers: int = 2
    vocab_size: int = 4096

    # Generate mode.
    temperature: float = 0.8
    max_new_tokens: int = 32
    # Serve-mode sampling (ISSUE 15): per-slot top-k cutoff (0 = off);
    # --temperature is shared with generate mode. Per-request bodies on
    # the HTTP ingress override both.
    top_k: int = 0

    # Serve mode (continuous batching over a synthetic request trace).
    slots: int = 8           # concurrent cache slots (max in-flight requests)
    requests: int = 16       # synthetic trace length
    prompt_len: int = 32     # base prompt length of the trace
    prompt_jitter: int = 8   # +- jitter on prompt lengths (ragged prompts)
    arrival_every: int = 0   # ticks between arrivals (0 = all queued at start)
    prefill_chunk: int = 256  # max prompt tokens one tick writes per slot
    prefill_budget: Optional[int] = None  # per-tick prompt-token budget
    admission: str = "chunked"  # "chunked" (stall-free) | "whole" (legacy)
    slo_ttft: float = 1.0    # TTFT target (s) for the goodput SLO
    slo_tbt: float = 0.2     # worst inter-token-gap target (s), ditto
    prefix_cache: bool = False  # radix prefix KV reuse across requests
    prefix_block: int = 64   # pool block granularity (tokens, pow2)
    prefix_share: float = 0.0  # trace: fraction of requests sharing a prefix
    prefix_len: int = 0      # trace: shared prefix length (tokens)
    kv_layout: str = "paged"  # paged (one block pool) | contiguous (PR-5)
    kv_block: Optional[int] = None  # tokens per pool block (pow2; None ->
    #                                 prefix-block with the cache on, else 64)
    kv_blocks: Optional[int] = None  # TOTAL pool capacity in blocks (None ->
    #                                  slots * ceil(cache_len / kv_block))
    kv_shard: str = "replicated"  # replicated | seq — 'seq' range-partitions
    #                               the paged pool (and its allocator) across
    #                               the mesh's seq axis; decode merges shard
    #                               partials with the tree monoid (ISSUE 18)
    # Hierarchical KV tiering (ISSUE 13): radix eviction demotes blocks
    # onto a host-RAM tier instead of freeing them; a later prefix hit
    # restores them with one batched H2D scatter.
    host_blocks: int = 0     # host-tier capacity in blocks (0 = no tier)
    kv_tiering: str = "on"   # on | off — off ignores --host-blocks (the
    #                          bench's A/B switch at one config)
    speculate: bool = False  # draft-and-verify speculative decoding
    draft_k: int = 4         # max draft tokens per slot per verify tick
    drafter: str = "ngram"   # ngram | ngram-tree | model
    # HTTP ingress (ISSUE 10): --serve-http turns serve mode into a live
    # streaming front-end instead of a synthetic-trace run.
    serve_http: Optional[int] = None  # port (0 = OS-picked, logged)
    max_queue: int = 64      # ingress admission-queue bound (429 past it)
    default_deadline: Optional[float] = None  # seconds; None = no default
    # Fleet serving (ISSUE 11): N in-process replica engines behind the
    # cache-aware router.
    serve_fleet: bool = False
    replicas: int = 2        # replica engines under --serve-fleet
    router_port: int = 0     # router HTTP port (0 = OS-picked, logged)
    affinity: str = "on"     # prefix-affinity routing: on | off
    # Disaggregated prefill/decode (ISSUE 12): split-phase engine pools
    # over one shared block pool, zero-copy KV handoff.
    serve_disagg: bool = False
    prefill_slots: int = 1   # prefill-pool slots under --serve-disagg
    decode_slots: Optional[int] = None  # decode-pool slots (None ->
    #                                     slots - prefill_slots)

    # Host data pipeline (train mode).
    host_data: bool = False
    data: Optional[str] = None       # path to a flat binary token corpus
    data_dtype: str = "int32"        # on-disk token width: int32 | uint16

    # Checkpointing (train mode).
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 1
    resume: bool = False

    # Observability.
    log_level: str = "info"
    log_file: Optional[str] = None
    all_processes: bool = False
    profile_dir: Optional[str] = None
    metrics_out: Optional[str] = None   # JSON metrics snapshot at exit
    trace_events: Optional[str] = None  # Chrome-trace JSONL span sink
    metrics_port: Optional[int] = None  # live /metrics HTTP exporter
    flight_out: Optional[str] = None    # tick flight-recorder dump sink

    def mesh_axes(self) -> Optional[Dict[str, int]]:
        return parse_mesh_spec(self.mesh) if self.mesh else None

    def resolved_kv_heads(self) -> int:
        return self.heads if self.kv_heads is None else self.kv_heads

    def resolved_quant_kernel(self) -> Optional[str]:
        """kv_quant → q8 kernel name (the one home of that mapping):
        'int8' → 'q8q' (int8-MXU, fastest), 'int8-cast' → 'q8' (bf16-cast),
        'none' → None. Programmatic configs bypass argparse's choices, so
        an unknown value raises here rather than silently running int8."""
        kernels = {"none": None, "int8": "q8q", "int8-cast": "q8"}
        if self.kv_quant not in kernels:
            raise ValueError(
                f"kv_quant must be one of {sorted(kernels)}, "
                f"got {self.kv_quant!r}"
            )
        return kernels[self.kv_quant]


def build_arg_parser() -> argparse.ArgumentParser:
    d = RunConfig()
    p = argparse.ArgumentParser(
        prog="tree_attention_tpu",
        # No abbreviations: --launch respawns the command with the flag
        # stripped by literal match; an abbreviated form surviving the strip
        # would recurse (and ambiguous prefixes are a footgun regardless).
        allow_abbrev=False,
        description=(
            "TPU-native sequence-parallel tree attention driver. With no "
            "flags, reproduces the reference workload (decode over a "
            f"{d.seq_len}-token context, {d.heads} heads × {d.head_dim})."
        ),
    )
    p.add_argument("--mode",
                   choices=["decode", "train", "generate", "bench", "serve"],
                   default=d.mode)
    p.add_argument("--device", choices=["auto", "tpu", "cpu"], default=d.device)
    p.add_argument("--mesh", default=d.mesh, metavar="SPEC",
                   help="named mesh axes, e.g. seq=8 or data=2,seq=2,model=2")
    p.add_argument("--n-virtual-cpu", type=int, default=d.n_virtual_cpu,
                   metavar="N", help="emulate N CPU devices (forces --device=cpu)")
    p.add_argument("--launch", type=int, default=d.launch, metavar="N",
                   help="spawn N coordinated local processes (the multi-host "
                        "shape: one jax.distributed cluster, devices pooled "
                        "across processes) and run this command in each; a "
                        "rank that dies fail-fast-kills its peers")
    p.add_argument("--launch-timeout", type=float, default=d.launch_timeout,
                   metavar="SEC", help="deadline for the whole --launch run; "
                   "ranks alive at expiry are killed (status 124)")
    p.add_argument("--heartbeat-stall", type=float, default=d.heartbeat_stall,
                   metavar="SEC", help="hang watchdog for --launch: a rank "
                   "making no progress (no heartbeat; the train loop beats "
                   "once per step) for SEC seconds gets the job killed, "
                   "stalled ranks reporting status 125 — catches the "
                   "all-ranks-alive collective deadlock the fail-fast "
                   "supervisor cannot see. Size it for jit compile time.")
    p.add_argument("--restarts", type=int, default=d.restarts, metavar="K",
                   help="elastic recovery for --launch: after a failed "
                   "attempt (crash/deadline/stall) relaunch the whole gang "
                   "up to K more times; with --ckpt-dir the children resume "
                   "from the latest checkpoint, so a restart is a resume, "
                   "not a redo")
    p.add_argument("--batch", type=int, default=d.batch)
    p.add_argument("--seq-len", type=int, default=d.seq_len)
    p.add_argument("--q-len", type=int, default=d.q_len)
    p.add_argument("--heads", type=int, default=d.heads)
    p.add_argument("--kv-heads", type=int, default=d.kv_heads,
                   help="GQA KV head count (default: same as --heads)")
    p.add_argument("--head-dim", type=int, default=d.head_dim)
    p.add_argument("--causal", action="store_true", default=d.causal)
    p.add_argument("--dtype", choices=["bfloat16", "float16", "float32"],
                   default=d.dtype)
    p.add_argument("--impl",
                   choices=["auto", "naive", "blockwise", "pallas",
                            "pallas_decode"],
                   default=d.impl)
    p.add_argument("--block-size", type=int, default=d.block_size,
                   help="KV tile length (default: per-impl tuned value)")
    p.add_argument("--kv-quant", choices=["none", "int8", "int8-cast"],
                   default=d.kv_quant,
                   help="decode: int8-quantize the KV buffer; generate: "
                        "quantize the cache after prefill (per-channel "
                        "scales; halves the KV stream). 'int8' runs the "
                        "int8-MXU q8q kernel (fastest); 'int8-cast' the "
                        "bf16-cast q8 kernel (minimum int8 error)")
    p.add_argument("--seq-layout", choices=["contiguous", "zigzag"],
                   default=d.seq_layout,
                   help="train mode: sequence layout over the seq mesh axis "
                        "(zigzag balances causal work across shards)")
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--iters", type=int, default=d.iters)
    p.add_argument("--warmup", type=int, default=d.warmup)
    p.add_argument("--comparator", choices=["none", "ring", "ring-decode"],
                   default=d.comparator,
                   help="bench mode: race tree against comparators and report "
                        "ratios — 'ring' on the training shape (fwd+bwd), "
                        "'ring-decode' on the decode shape (replicated Q, "
                        "with collective counts and bytes-on-wire from the "
                        "compiled HLO)")
    p.add_argument("--steps", type=int, default=d.steps, help="train-mode steps")
    p.add_argument("--model-dim", type=int, default=d.model_dim)
    p.add_argument("--n-layers", type=int, default=d.n_layers)
    p.add_argument("--vocab-size", type=int, default=d.vocab_size)
    p.add_argument("--temperature", type=float, default=d.temperature,
                   help="generate/serve mode: sampling temperature "
                        "(0 = greedy; serve mode threads per-slot PRNG "
                        "keys so fixed-seed runs resample bit-for-bit)")
    p.add_argument("--top-k", type=int, default=d.top_k,
                   help="serve mode: restrict sampling to the k highest "
                        "logits per step (0 = off; only applies when "
                        "--temperature > 0). Per-request bodies on "
                        "--serve-http override both knobs")
    p.add_argument("--max-new-tokens", type=int, default=d.max_new_tokens,
                   help="generate/serve mode: number of tokens to sample "
                        "per request")
    p.add_argument("--slots", type=int, default=d.slots,
                   help="serve mode: concurrent cache slots — the fixed "
                        "batch the continuous-batching engine decodes every "
                        "tick; the cache is sized from the trace "
                        "(max prompt + max-new-tokens, rounded to the "
                        "mesh's seq-shard multiple)")
    p.add_argument("--requests", type=int, default=d.requests,
                   help="serve mode: synthetic request-trace length")
    p.add_argument("--prompt-len", type=int, default=d.prompt_len,
                   help="serve mode: base prompt length of the trace")
    p.add_argument("--prompt-jitter", type=int, default=d.prompt_jitter,
                   help="serve mode: +- jitter on prompt lengths (ragged "
                        "prompts exercise per-slot cache offsets)")
    p.add_argument("--arrival-every", type=int, default=d.arrival_every,
                   help="serve mode: decode ticks between request arrivals "
                        "(0 = the whole trace is queued at start)")
    p.add_argument("--prefill-chunk", type=int, default=d.prefill_chunk,
                   help="serve mode: max prompt tokens one tick may write "
                        "for one slot — smaller chunks bound the latency "
                        "spike a long prompt inflicts on live slots")
    p.add_argument("--prefill-budget", type=int, default=d.prefill_budget,
                   help="serve mode: max TOTAL prompt tokens per tick "
                        "across prefilling slots (default: slots * chunk, "
                        "i.e. every prefilling slot advances one chunk) — "
                        "the Sarathi-style stall-free token budget")
    p.add_argument("--admission", choices=["chunked", "whole"],
                   default=d.admission,
                   help="serve mode: 'chunked' fuses prefill chunks into "
                        "the per-tick mixed step (stall-free); 'whole' is "
                        "the legacy blocking whole-prompt prefill + insert")
    p.add_argument("--slo-ttft", type=float, default=d.slo_ttft,
                   metavar="SEC",
                   help="serve mode: TTFT target of the goodput SLO — a "
                        "retired request counts as good iff its first "
                        "token arrived within SEC and no inter-token gap "
                        "exceeded --slo-tbt")
    p.add_argument("--slo-tbt", type=float, default=d.slo_tbt,
                   metavar="SEC",
                   help="serve mode: worst-inter-token-gap target of the "
                        "goodput SLO (see --slo-ttft)")
    p.add_argument("--prefix-cache", action="store_true",
                   default=d.prefix_cache,
                   help="serve mode: enable the radix prefix KV cache — "
                        "admissions reuse KV blocks of previously served "
                        "prompt prefixes (one pool gather replaces their "
                        "prefill; RadixAttention, arXiv:2312.07104)")
    p.add_argument("--prefix-block", type=int, default=d.prefix_block,
                   help="serve mode: prefix pool block size in tokens "
                        "(power of two; the match/publish granularity)")
    p.add_argument("--kv-layout", choices=["paged", "contiguous"],
                   default=d.kv_layout,
                   help="serve mode: 'paged' (default) holds every "
                        "slot's KV as a block table over ONE ref-counted "
                        "pool (PagedAttention, arXiv:2309.06180) — "
                        "copy-free prefix hits, on-demand allocation, "
                        "admissions defer when the pool is full; "
                        "'contiguous' keeps the per-slot regions + "
                        "gather hits")
    p.add_argument("--kv-block", type=int, default=d.kv_block,
                   help="serve mode: tokens per KV pool block (power of "
                        "two; default --prefix-block with the prefix "
                        "cache on, else 64)")
    p.add_argument("--kv-blocks", type=int, default=d.kv_blocks,
                   help="serve mode: TOTAL paged pool capacity in blocks "
                        "— the one KV memory budget slots and the prefix "
                        "cache share (default: slots * ceil(cache_len / "
                        "kv_block), the contiguous layout's bytes). "
                        "Smaller over-subscribes: admissions wait for "
                        "free blocks instead of failing")
    p.add_argument("--kv-shard", choices=["replicated", "seq"],
                   default=d.kv_shard,
                   help="serve mode: 'seq' range-partitions the paged KV "
                        "pool across the mesh's sequence axis — each "
                        "shard holds blocks/W pool rows plus its own "
                        "free-list shard, decode computes per-shard "
                        "flash partials over LOCAL blocks only and "
                        "merges them with the tree monoid (one pmax + "
                        "two psum per tick). Max servable context grows "
                        "~linearly with W at fixed per-device KV bytes. "
                        "Requires --kv-layout paged; 'replicated' "
                        "(default) keeps the pool on every shard")
    p.add_argument("--host-blocks", type=int, default=d.host_blocks,
                   help="serve mode: host-RAM KV tier capacity in blocks "
                        "(0 = no tier). With the paged layout + prefix "
                        "cache, radix eviction DEMOTES refcount-0 blocks "
                        "into pinned host memory (async D2H, one batched "
                        "gather per tick) instead of freeing them, and a "
                        "prefix hit on a demoted path restores it with "
                        "one batched H2D scatter — the effective prefix "
                        "cache becomes host-RAM-sized (SGLang's "
                        "hierarchical cache direction)")
    p.add_argument("--kv-tiering", choices=["on", "off"],
                   default=d.kv_tiering,
                   help="serve mode: 'off' ignores --host-blocks (radix "
                        "eviction frees blocks, the pre-tiering "
                        "behavior) — the A/B switch the tiered-KV bench "
                        "flips at one otherwise-identical config")
    p.add_argument("--speculate", action="store_true", default=d.speculate,
                   help="serve mode: draft-and-verify speculative "
                        "decoding (arXiv:2211.17192) on the mixed-Tq "
                        "tick — a host drafter proposes tokens, ONE "
                        "verify step scores them all, accepted prefixes "
                        "commit in a burst, rejections roll back. Greedy "
                        "only (--temperature 0); committed tokens are "
                        "token-for-token identical to non-speculative "
                        "decode")
    p.add_argument("--draft-k", type=int, default=d.draft_k,
                   help="serve mode: max draft tokens per slot per "
                        "verify tick (1..31); one verify commits 1 to "
                        "draft_k+1 tokens")
    p.add_argument("--drafter", choices=["ngram", "ngram-tree", "model"],
                   default=d.drafter,
                   help="serve mode: 'ngram' = prompt-lookup over the "
                        "slot's own history (zero extra model); "
                        "'ngram-tree' = multi-branch token trees "
                        "verified under the tree-attention ancestor "
                        "mask (SpecInfer, arXiv:2305.09781); 'model' = "
                        "a shrunk draft transformer (half depth, same "
                        "vocab, --seed+3)")
    p.add_argument("--serve-http", type=int, default=d.serve_http,
                   metavar="PORT",
                   help="serve mode: run the streaming HTTP ingress on "
                        "localhost:PORT (0 picks a free port, logged) "
                        "instead of draining a synthetic trace — "
                        "OpenAI-compatible POST /v1/completions with SSE "
                        "token streaming, client-disconnect cancellation, "
                        "per-request deadlines, 429+Retry-After "
                        "backpressure; SIGTERM drains gracefully "
                        "(finish in-flight, flush telemetry)")
    p.add_argument("--max-queue", type=int, default=d.max_queue,
                   help="--serve-http: max requests queued ahead of "
                        "first token; submissions past it get 429 with "
                        "Retry-After derived from queue depth and the "
                        "SLO monitor's windowed TTFT")
    p.add_argument("--default-deadline", type=float,
                   default=d.default_deadline, metavar="SEC",
                   help="--serve-http: deadline for requests that do "
                        "not carry their own deadline_s — expired in "
                        "queue they are rejected, expired in flight "
                        "retired with outcome 'deadline'")
    p.add_argument("--serve-fleet", action="store_true",
                   default=d.serve_fleet,
                   help="serve mode: run --replicas in-process replica "
                        "engines behind the cache-aware HTTP router "
                        "(prefix-affinity load balancing, SGLang arXiv:"
                        "2312.07104) instead of one ingress — same "
                        "OpenAI-compatible POST /v1/completions on the "
                        "router port; SIGTERM rolls the whole fleet "
                        "down gracefully")
    p.add_argument("--replicas", type=int, default=d.replicas,
                   help="--serve-fleet: replica engine count (each gets "
                        "its own slots/cache/prefix pool; total capacity "
                        "scales linearly)")
    p.add_argument("--router-port", type=int, default=d.router_port,
                   metavar="PORT",
                   help="--serve-fleet: router HTTP port (0 picks a "
                        "free port, logged)")
    p.add_argument("--affinity", choices=["on", "off"],
                   default=d.affinity,
                   help="--serve-fleet: 'on' routes requests to the "
                        "replica whose radix cache already holds their "
                        "longest prefix (least-loaded fallback with "
                        "hysteresis); 'off' is pure least-loaded round-"
                        "robin — the dilution baseline")
    p.add_argument("--serve-disagg", action="store_true",
                   default=d.serve_disagg,
                   help="serve mode: disaggregated prefill/decode "
                        "(DistServe arXiv:2401.09670, Splitwise arXiv:"
                        "2311.18677) — a prefill pool (--prefill-slots) "
                        "runs admission + chunked prefill only and hands "
                        "finished requests to a decode pool "
                        "(--decode-slots) by zero-copy paged-block "
                        "ownership transfer over ONE shared --kv-blocks "
                        "pool; decode ticks never carry prefill rows, so "
                        "TBT stops paying for admission storms. "
                        "Composable with --serve-http (the ingress "
                        "drives the disaggregated pair unchanged); "
                        "paged layout only")
    p.add_argument("--prefill-slots", type=int, default=d.prefill_slots,
                   help="--serve-disagg: prefill-pool slot count "
                        "(prompts concurrently in chunked prefill or "
                        "parked for handoff)")
    p.add_argument("--decode-slots", type=int, default=d.decode_slots,
                   help="--serve-disagg: decode-pool slot count "
                        "(default: --slots minus --prefill-slots, so "
                        "--slots stays the total-capacity knob)")
    p.add_argument("--prefix-share", type=float, default=d.prefix_share,
                   help="serve mode: fraction of the synthetic trace's "
                        "requests drawing their prompt head from a shared "
                        "prefix (models shared system prompts)")
    p.add_argument("--prefix-len", type=int, default=d.prefix_len,
                   help="serve mode: length of the trace's shared prefix "
                        "in tokens (0 = no sharing)")
    p.add_argument("--host-data", action="store_true", default=d.host_data,
                   help="train mode: feed batches from the native prefetching "
                        "host pipeline instead of on-device RNG")
    p.add_argument("--data", default=d.data, metavar="PATH",
                   help="train mode: mmap'd binary token corpus to sample "
                        "batches from (overrides --host-data's synthetic "
                        "tokens; token ids must be < --vocab-size)")
    p.add_argument("--data-dtype", choices=["int32", "uint16"],
                   default=d.data_dtype,
                   help="on-disk token width of --data")
    p.add_argument("--ckpt-dir", default=d.ckpt_dir,
                   help="train mode: checkpoint directory (enables saving)")
    p.add_argument("--ckpt-every", type=int, default=d.ckpt_every,
                   help="save every N steps")
    p.add_argument("--resume", action="store_true", default=d.resume,
                   help="resume from the latest checkpoint in --ckpt-dir")
    p.add_argument("--log-level", choices=["debug", "info", "warning", "error"],
                   default=d.log_level)
    p.add_argument("--log-file", default=d.log_file,
                   help="rotating file sink (the reference's tree_attention_log.log)")
    p.add_argument("--all-processes", action="store_true", default=d.all_processes,
                   help="log from every host, not just process 0")
    p.add_argument("--profile-dir", default=d.profile_dir,
                   help="capture a jax.profiler trace into this directory")
    p.add_argument("--metrics-out", default=d.metrics_out, metavar="PATH",
                   help="write the telemetry registry (tokens decoded, "
                        "collective payload bytes, kernel builds, guard "
                        "verdicts, ...) as JSON at exit; under --launch "
                        "each rank writes PATH.pK")
    p.add_argument("--trace-events", default=d.trace_events, metavar="PATH",
                   help="emit host-side spans as Chrome-trace-format JSONL "
                        "(one JSON event per line; load in Perfetto "
                        "alongside a --profile-dir device trace)")
    p.add_argument("--metrics-port", type=int, default=d.metrics_port,
                   metavar="PORT",
                   help="serve the live telemetry HTTP endpoint on "
                        "localhost:PORT — /metrics (Prometheus text), "
                        "/metrics.json (registry snapshot), /healthz "
                        "(tick liveness), /flight (flight-recorder ring); "
                        "0 picks a free port (logged). Arms the registry "
                        "and flight recorder even without --metrics-out")
    p.add_argument("--flight-out", default=d.flight_out, metavar="PATH",
                   help="arm the serving tick flight recorder and dump "
                        "its ring (last ticks: occupancy, slot states, "
                        "chunk plan, queue depth) to PATH at exit, on "
                        "engine error, and on SIGTERM/SIGUSR1")
    return p


def parse_args(argv: Optional[Sequence[str]] = None) -> RunConfig:
    ns = build_arg_parser().parse_args(argv)
    fields = {f.name for f in dataclasses.fields(RunConfig)}
    return RunConfig(**{k: v for k, v in vars(ns).items() if k in fields})
