"""Shared KV-blocking and mask helpers for the blockwise forward/backward.

The forward (``reference.attention_blockwise``) and the flash backward
(``vjp.attention_bwd_blockwise``) must mask and pad *identically* or gradients
silently diverge from the forward — so the logic lives once, here.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# The one definition of the masking sentinel and the TPU vector lane width —
# every impl must mask with the same -inf and tile to the same lane count.
NEG_INF = float("-inf")
LANES = 128


def matmul_precision(*dtypes):
    """Contraction precision for the ops-layer matmuls, by operand dtype.

    bf16 operands need nothing: the MXU multiplies them exactly and
    ``preferred_element_type=f32`` accumulates in f32 — that is already the
    best bf16 inputs can get, and requesting HIGHEST instead makes XLA upcast
    to a multi-pass f32 contraction (~4x slower) and Mosaic reject the matmul
    outright ("Bad lhs type").

    Anything else (f32/f16/f64) must pin HIGHEST: the default matmul
    precision may silently lower the contraction to a single bf16 pass
    (observed ~5e-3 relative logit error on both the TPU MXU and, for some
    contraction layouts, the CPU backend) — unacceptable in an
    exact-attention library whose merge currency is an f32 lse.
    """
    from jax import lax

    if all(jnp.dtype(d) == jnp.bfloat16 for d in dtypes):
        return None
    return lax.Precision.HIGHEST


def pad_to_block(x: jax.Array, dim: int, block: int) -> jax.Array:
    """Zero-pad ``dim`` up to a multiple of ``block``."""
    pad = (-x.shape[dim]) % block
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[dim] = (0, pad)
    return jnp.pad(x, widths)


def split_kv_blocks(
    k: jax.Array, v: jax.Array, block: int
) -> Tuple[jax.Array, jax.Array, int, int]:
    """Reshape (B, Hkv, Tk, D) K/V into the (num_blocks, B, Hkv, blk, D) scan
    layout, padding the tail block. Returns (kb, vb, num_blocks, blk)."""
    B, Hkv, Tk, D = k.shape
    blk = min(block, Tk)
    kp = pad_to_block(k, 2, blk)
    vp = pad_to_block(v, 2, blk)
    num_blocks = kp.shape[2] // blk
    kb = kp.reshape(B, Hkv, num_blocks, blk, D).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(B, Hkv, num_blocks, blk, D).transpose(2, 0, 1, 3, 4)
    return kb, vb, num_blocks, blk


def tile_geometry(qi, ki, block_q: int, block_k: int, q_offset, kv_offset):
    """Per-tile global positions for the Pallas kernels (rows = Q, cols = K).

    Returns ``(row_pos, col_idx, col_pos)`` of shape (block_q, block_k):
    global query positions, local key column indices (for the ragged-tail
    check against Tk), and global key positions. Forward and both backward
    kernels must use this one definition or their masks diverge.
    """
    q_start = qi * block_q
    k_start = ki * block_k
    row_pos = q_offset + q_start + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    col_idx = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    col_pos = kv_offset + col_idx
    return row_pos, col_idx, col_pos


def tile_live(qi, ki, block_q: int, block_k: int, q_offset, kv_offset,
              causal: bool):
    """Whether a (Q-tile, KV-tile) pair has any visible entry under causality:
    live iff the most-visible corner (last row, first col) is unmasked."""
    if not causal:
        return True
    return (q_offset + qi * block_q + block_q - 1) >= (kv_offset + ki * block_k)


def tile_mask(
    tq: int,
    blk: int,
    blk_idx,
    tk: int,
    q_offset,
    kv_offset,
    causal: bool,
) -> jax.Array:
    """(tq, blk) visibility mask for one KV tile.

    Combines the ragged-tail range check (padded keys beyond ``tk`` are
    invalid) with cross-shard causality: query global position
    ``q_offset + row`` sees key global position ``kv_offset + start + col``
    iff q_pos >= k_pos.
    """
    start = blk_idx * blk
    local_col = start + lax.broadcasted_iota(jnp.int32, (tq, blk), 1)
    valid = local_col < tk
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (tq, blk), 0)
        valid = valid & (q_pos >= kv_offset + local_col)
    return valid
