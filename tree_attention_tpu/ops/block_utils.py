"""Shared KV-blocking and mask helpers for the blockwise forward/backward.

The forward (``reference.attention_blockwise``) and the flash backward
(``vjp.attention_bwd_blockwise``) must mask and pad *identically* or gradients
silently diverge from the forward — so the logic lives once, here.
"""

from __future__ import annotations

import numbers
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# The one definition of the masking sentinel and the TPU vector lane width —
# every impl must mask with the same -inf and tile to the same lane count.
NEG_INF = float("-inf")
LANES = 128


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across its rename (older JAX spells it
    ``TPUCompilerParams``; the fields are the same). One home, so every
    kernel's ``compiler_params=`` stays version-portable."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def matmul_precision(*dtypes):
    """Contraction precision for the ops-layer matmuls, by operand dtype.

    bf16 operands need nothing: the MXU multiplies them exactly and
    ``preferred_element_type=f32`` accumulates in f32 — that is already the
    best bf16 inputs can get, and requesting HIGHEST instead makes XLA upcast
    to a multi-pass f32 contraction (~4x slower) and Mosaic reject the matmul
    outright ("Bad lhs type").

    Anything else (f32/f16/f64) must pin HIGHEST: the default matmul
    precision may silently lower the contraction to a single bf16 pass
    (observed ~5e-3 relative logit error on both the TPU MXU and, for some
    contraction layouts, the CPU backend) — unacceptable in an
    exact-attention library whose merge currency is an f32 lse.
    """
    from jax import lax

    if all(jnp.dtype(d) == jnp.bfloat16 for d in dtypes):
        return None
    return lax.Precision.HIGHEST


def pad_to_block(x: jax.Array, dim: int, block: int) -> jax.Array:
    """Zero-pad ``dim`` up to a multiple of ``block``."""
    pad = (-x.shape[dim]) % block
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[dim] = (0, pad)
    return jnp.pad(x, widths)


def split_kv_blocks(
    k: jax.Array, v: jax.Array, block: int
) -> Tuple[jax.Array, jax.Array, int, int]:
    """Reshape (B, Hkv, Tk, D) K/V into the (num_blocks, B, Hkv, blk, D) scan
    layout, padding the tail block. Returns (kb, vb, num_blocks, blk)."""
    B, Hkv, Tk, D = k.shape
    blk = min(block, Tk)
    kp = pad_to_block(k, 2, blk)
    vp = pad_to_block(v, 2, blk)
    num_blocks = kp.shape[2] // blk
    kb = kp.reshape(B, Hkv, num_blocks, blk, D).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(B, Hkv, num_blocks, blk, D).transpose(2, 0, 1, 3, 4)
    return kb, vb, num_blocks, blk


def tile_geometry(qi, ki, block_q: int, block_k: int, q_offset, kv_offset):
    """Per-tile global positions for the Pallas kernels (rows = Q, cols = K).

    Returns ``(row_pos, col_idx, col_pos)`` in **broadcast form** —
    ``row_pos`` is ``(block_q, 1)``, ``col_idx``/``col_pos`` are
    ``(1, block_k)`` — so a mask like ``row_pos >= col_pos`` materialises
    one ``(block_q, block_k)`` compare instead of two full-tile i32 iotas
    first (~4 VPU passes down to ~1; measured 2026-07-31, the full-tile
    form cost the 4k causal fwd kernel several percent and an attempted
    ``lax.cond`` skip cost 45%). Forward and both backward kernels must use
    this one definition or their masks diverge.
    """
    q_start = qi * block_q
    k_start = ki * block_k
    row_pos = q_offset + q_start + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )
    col_idx = k_start + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    col_pos = kv_offset + col_idx
    return row_pos, col_idx, col_pos


def tile_live(qi, ki, block_q: int, block_k: int, q_offset, kv_offset,
              causal: bool):
    """Whether a (Q-tile, KV-tile) pair has any visible entry under causality:
    live iff the most-visible corner (last row, first col) is unmasked."""
    if not causal:
        return True
    return (q_offset + qi * block_q + block_q - 1) >= (kv_offset + ki * block_k)


def mask_scores(s, qi, ki, block_q: int, block_k: int, q_offset, kv_offset,
                tk: int, causal: bool):
    """Ragged-tail + causal masking for a ``(block_q, block_k)`` score tile.

    Static no-op for non-causal divisible shapes. Built from the broadcast
    geometry (see :func:`tile_geometry`): the mask is one broadcast compare
    + select, not full-tile iota materialisation. (A ``lax.cond``
    interior-tile skip was tried and REGRESSED the 4k causal fwd kernel 45%
    on v5e — Mosaic's vector-operand branch join costs more than the mask
    it saves — and VMEM-OOM'd the bwd kernels at 16k; don't reintroduce
    it.) One definition shared by the fwd and both bwd kernels.
    """
    needs_ragged = tk % block_k != 0
    if not causal and not needs_ragged:
        return s
    row_pos, col_idx, col_pos = tile_geometry(
        qi, ki, block_q, block_k, q_offset, kv_offset
    )
    if needs_ragged and causal:
        valid = (col_idx < tk) & (row_pos >= col_pos)
    elif causal:
        valid = row_pos >= col_pos
    else:
        valid = jnp.broadcast_to(col_idx < tk, s.shape)
    return jnp.where(valid, s, NEG_INF)


def offsets_smem(q_offset, kv_offset, batch: int) -> jax.Array:
    """(2, B) int32 SMEM operand: per-batch [q_offset | kv_offset] rows.

    Scalars broadcast to every batch row; a ``(B,)`` vector gives each row
    (cache slot) its own global position — the ragged-batch contract shared
    by every offset-taking Pallas kernel (a kernel with batch-major grid
    dim 0 indexes column ``program_id(0) // heads_per_batch``)."""
    q_off = jnp.broadcast_to(jnp.asarray(q_offset, jnp.int32), (batch,))
    kv_off = jnp.broadcast_to(jnp.asarray(kv_offset, jnp.int32), (batch,))
    return jnp.stack([q_off, kv_off])


def static_offsets(q_offset, kv_offset) -> bool:
    """Whether both causal shard offsets are compile-time integers.

    True on the unsharded path (offsets are literals); False inside
    ``shard_map``, where at least one offset is a traced ``axis_index``
    product. Static offsets let the Pallas index maps cull causally dead
    tiles at the *grid* level — dead iterations map to the block the next
    live step will need (block 0 for trailing-dead ``culled_ki``, the
    first live block for leading-dead ``culled_qi``), so Pallas's
    revisiting pipeline elides the repeats and the dead time prefetches —
    instead of only skipping their compute via ``pl.when``.
    """
    return isinstance(q_offset, numbers.Integral) and isinstance(
        kv_offset, numbers.Integral
    )


def causal_last_live_k(qi, block_q: int, block_k: int, q_offset: int,
                       kv_offset: int, n_k: int):
    """Last causally live KV-tile index for Q tile ``qi`` (static offsets).

    Derived from :func:`tile_live`: live iff
    ``q_offset + qi·bq + bq − 1 >= kv_offset + ki·bk``. Clamped to
    ``[0, n_k−1]``; a fully-masked Q row clamps to 0 (its compute is skipped
    either way, the clamp just keeps the index in range).
    """
    hi = (q_offset - kv_offset + qi * block_q + block_q - 1) // block_k
    return jnp.clip(hi, 0, n_k - 1)


def causal_first_live_q(ki, block_q: int, block_k: int, q_offset: int,
                        kv_offset: int, n_q: int):
    """First causally live Q-tile index for KV tile ``ki`` (static offsets).

    The ceil counterpart of :func:`causal_last_live_k`, clamped to
    ``[0, n_q−1]``.
    """
    lo = -((q_offset + block_q - 1 - kv_offset - ki * block_k) // block_q)
    return jnp.clip(lo, 0, n_q - 1)


def culled_ki(qi, ki, cull, block_q: int, block_k: int, n_k: int):
    """KV-tile index with grid-level causal culling (index-map side).

    ``cull`` is ``(q_offset, kv_offset)`` as ints or None. Dead tiles past
    the diagonal all map to block **0** — the first block the NEXT Q row
    needs — so the row's dead grid steps (which run in ~no time; their
    compute is gated off by ``pl.when(tile_live(...))``) become prefetch
    time for the next row instead of a cold-fetch bubble at its first live
    step. One DMA fires on the diagonal→0 transition; the remaining dead
    steps and the next row's ``ki=0`` step reuse the resident block (the
    Pallas revisiting pipeline elides repeats). Clamping dead tiles to the
    row's last live block instead (the pre-r5 scheme) elides their DMA too
    but leaves the next row starting cold — measured as most of a ~9%
    fwd-MFU gap vs the JAX-bundled kernel, whose causal ``kv_index_map``
    uses this same prefetch-zero trick. The one definition shared by the
    fwd and dQ kernels — they must cull identically or diverge silently.
    """
    if cull is None:
        return ki
    live = ki <= causal_last_live_k(
        qi, block_q, block_k, cull[0], cull[1], n_k
    )
    return jnp.where(live, ki, 0)


def culled_qi(ki, qi, cull, block_q: int, block_k: int, n_q: int):
    """Q-tile index with grid-level causal culling (dKV mirror of
    :func:`culled_ki`): dead tiles *before* the diagonal repeat the first
    live block of their segment."""
    if cull is None:
        return qi
    return jnp.maximum(
        qi, causal_first_live_q(ki, block_q, block_k, cull[0], cull[1], n_q)
    )


def tile_mask(
    tq: int,
    blk: int,
    blk_idx,
    tk: int,
    q_offset,
    kv_offset,
    causal: bool,
) -> jax.Array:
    """(tq, blk) visibility mask for one KV tile.

    Combines the ragged-tail range check (padded keys beyond ``tk`` are
    invalid) with cross-shard causality: query global position
    ``q_offset + row`` sees key global position ``kv_offset + start + col``
    iff q_pos >= k_pos.
    """
    start = blk_idx * blk
    local_col = start + lax.broadcasted_iota(jnp.int32, (tq, blk), 1)
    valid = local_col < tk
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (tq, blk), 0)
        valid = valid & (q_pos >= kv_offset + local_col)
    return valid
