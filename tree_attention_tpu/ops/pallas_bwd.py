"""Pallas TPU flash-attention backward kernels.

Two kernels, the standard split (SURVEY.md §7 hard part 1):

- **dQ kernel** — grid ``(B·Hq, Tq/bq, Tk/bk)``: for one Q tile, stream KV
  tiles, accumulate ``dq += ds·K·scale`` in VMEM scratch.
- **dKV kernel** — grid ``(B·Hkv, Tk/bk, G·Tq/bq)``: for one KV tile, stream
  every query head of the group and every Q tile, accumulate
  ``dk += dsᵀ·Q·scale`` and ``dv += pᵀ·dO`` in scratch. GQA reduction over
  the group happens in-register — KV gradients never materialise per
  query head.

Both recompute ``p = exp(q·kᵀ·scale − lse)`` from the saved lse (no stored
probabilities), and consume a host-precomputed
``delta = rowsum(dO ⊙ O) − dlse`` — the lse-cotangent folding described in
:mod:`tree_attention_tpu.ops.vjp`. The two per-row f32 residuals ride ONE
128-lane tensor (lse in lane 0, delta in lane ``DELTA_LANE``): the dKV
kernel's Q-side blocks change every grid step, making residual reads its
dominant un-elidable HBM stream, and packing halves them. Padded query rows
are neutralised by padding lse with ``+inf`` (making ``p`` exactly 0 there);
padded key columns by the in-kernel range mask. Causally dead tiles skip
all compute via ``pl.when``, and with static offsets their DMAs are culled
at the grid level (see ``block_utils.culled_ki``/``culled_qi``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tree_attention_tpu.ops.block_utils import (
    culled_ki,
    culled_qi,
    mask_scores,
    pad_to_block,
    static_offsets,
    tile_live,
)

from tree_attention_tpu.ops.block_utils import (
    LANES as _LANES,
    matmul_precision,
    tpu_compiler_params,
)


DELTA_LANE = 64  # lane carrying delta in the packed residual (lse rides 0)


def _recompute_p_ds(q, k, v, dout, lse, delta, *, scale, causal,
                    qi, ki, block_q, block_k, q_offset, kv_offset, tk):
    """p and ds for one (Q-tile, KV-tile) pair, f32 results.

    Matmul operands stay in their storage dtype (bf16 rides the MXU fast
    path; a prior f32 upcast quarters throughput) and accumulate in f32.
    """
    s = lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=matmul_precision(q.dtype, k.dtype),
    ) * scale
    # Ragged-tail + causal masking (broadcast-form; the backward pays the
    # mask in BOTH kernels per tile pair, so its cost matters double here).
    s = mask_scores(s, qi, ki, block_q, block_k, q_offset, kv_offset, tk,
                    causal)
    # lse is padded with +inf on padded rows -> p == 0 there; masked cols give
    # exp(-inf - lse) == 0.
    p = jnp.exp(s - lse)
    dp = lax.dot_general(
        dout, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=matmul_precision(dout.dtype, v.dtype),
    )
    ds = p * (dp - delta)
    return p, ds


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, res_ref,
               dq_ref, dq_scr, *, scale, causal, tk, block_q, block_k,
               n_heads):
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)
    b = pl.program_id(0) // n_heads  # grid dim 0 runs over B*Hq
    q_offset, kv_offset = offs_ref[0, b], offs_ref[1, b]

    @pl.when(ki == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(tile_live(qi, ki, block_q, block_k, q_offset, kv_offset, causal))
    def _():
        _, ds = _recompute_p_ds(
            q_ref[0], k_ref[0], v_ref[0],
            do_ref[0], res_ref[0][:, :1],
            res_ref[0][:, DELTA_LANE:DELTA_LANE + 1],
            scale=scale, causal=causal, qi=qi, ki=ki,
            block_q=block_q, block_k=block_k,
            q_offset=q_offset, kv_offset=kv_offset, tk=tk,
        )
        dq_scr[...] += lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(k_ref.dtype, k_ref.dtype),
        ) * scale

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, res_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, tk, block_q, block_k, n_q, n_heads):
    ki, gq = pl.program_id(1), pl.program_id(2)
    n_gq = pl.num_programs(2)
    b = pl.program_id(0) // n_heads  # grid dim 0 runs over B*Hkv
    q_offset, kv_offset = offs_ref[0, b], offs_ref[1, b]

    @pl.when(gq == 0)
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # gq enumerates (g, qi) pairs — same decoding as the BlockSpec index maps.
    qi = gq % n_q

    @pl.when(tile_live(qi, ki, block_q, block_k, q_offset, kv_offset, causal))
    def _():
        p, ds = _recompute_p_ds(
            q_ref[0], k_ref[0], v_ref[0],
            do_ref[0], res_ref[0][:, :1],
            res_ref[0][:, DELTA_LANE:DELTA_LANE + 1],
            scale=scale, causal=causal, qi=qi, ki=ki,
            block_q=block_q, block_k=block_k,
            q_offset=q_offset, kv_offset=kv_offset, tk=tk,
        )
        dk_scr[...] += lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(q_ref.dtype, q_ref.dtype),
        ) * scale
        dv_scr[...] += lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(do_ref.dtype, do_ref.dtype),
        )

    @pl.when(gq == n_gq - 1)
    def _():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def attention_bwd_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    dlse: jax.Array,
    *,
    causal: bool,
    scale: Optional[float],
    q_offset=0,
    kv_offset=0,
    block_size: int = 512,
    block_q: int = 256,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas backward: same contract as ``attention_bwd_blockwise``.

    Static integer offsets under ``causal`` enable grid-level culling (see
    ``attention_pallas_fwd``): the dQ kernel repeats the last live KV block
    past the diagonal, the dKV kernel repeats the first live Q block before
    it, and the elided DMAs remove the dead half of the causal HBM traffic.
    """
    cull = (
        (int(q_offset), int(kv_offset))
        if causal and static_offsets(q_offset, kv_offset)
        else None
    )
    return _attention_bwd_pallas(
        q, k, v, out, lse, dout, dlse, causal=causal, scale=scale,
        q_offset=q_offset, kv_offset=kv_offset, block_size=block_size,
        block_q=block_q, interpret=interpret, cull=cull,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_size", "block_q", "interpret", "cull"
    ),
)
def _attention_bwd_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    dlse: jax.Array,
    *,
    causal: bool,
    scale: Optional[float],
    q_offset,
    kv_offset,
    block_size: int,
    block_q: int,
    interpret: Optional[bool],
    cull: Optional[Tuple[int, int]],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    B, Hq, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    G = Hq // Hkv
    s = (D ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if Tk == 0:
        return jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v)

    bq = min(block_q, max(Tq, 8))
    bk = min(block_size, max(Tk, _LANES))

    qp = pad_to_block(q.reshape(B * Hq, Tq, D), 1, bq)
    dop = pad_to_block(dout.reshape(B * Hq, Tq, D), 1, bq)
    kp = pad_to_block(k.reshape(B * Hkv, Tk, D), 1, bk)
    vp = pad_to_block(v.reshape(B * Hkv, Tk, D), 1, bk)
    tq_pad, tk_pad = qp.shape[1], kp.shape[1]
    n_q, n_k = tq_pad // bq, tk_pad // bk

    # delta with the lse cotangent folded in; +inf-pad lse so padded rows
    # recompute p == 0 (see module docstring).
    delta = (
        jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
        - dlse.astype(jnp.float32)
    ).reshape(B * Hq, Tq)
    pad_rows = tq_pad - Tq
    # Rows with no visible keys carry lse == -inf; the in-kernel recompute
    # would hit exp(-inf - (-inf)) == nan wherever the causal boundary
    # straddles a tile. Mapping them to +inf makes p exactly 0 for the whole
    # row — the correct vanishing gradient — same neutralisation as the
    # padded rows below.
    lse_f = jnp.where(jnp.isneginf(lse), jnp.inf, lse).reshape(B * Hq, Tq)
    if pad_rows:
        lse_f = jnp.pad(lse_f, ((0, 0), (0, pad_rows)), constant_values=jnp.inf)
        delta = jnp.pad(delta, ((0, 0), (0, pad_rows)))
    # Per-row scalars must ride a 128-lane axis (TPU tiling rejects (1, bq)
    # blocks of a 2-D (B*Hq, tq_pad) array: sublane dim 1 is neither
    # 8-aligned nor full). Rather than broadcasting lse and delta into two
    # full 128-lane tensors, both pack into ONE: lse in lane 0, delta in
    # lane DELTA_LANE. Residual HBM traffic is the dominant stream of the
    # dKV kernel (its Q-side blocks change every grid step, so nothing is
    # elided), and the f32 residuals outweigh the bf16 Q/dO tiles — packing
    # halves that cost.
    res_b = jnp.zeros((B * Hq, tq_pad, _LANES), jnp.float32)
    res_b = res_b.at[..., 0].set(lse_f).at[..., DELTA_LANE].set(delta)

    from tree_attention_tpu.ops.block_utils import offsets_smem

    # (2, B) per-batch offset columns — same ragged contract as the fwd
    # kernels (scalars broadcast; the kernels index their own batch row).
    offs = offsets_smem(q_offset, kv_offset, B)

    def kv_from_qrow(bh, *_rest):
        return bh // Hq * Hkv + (bh % Hq) // G

    def ki_live(qi, ki):
        return culled_ki(qi, ki, cull, bq, bk, n_k)

    # ---- dQ ----
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=s, causal=causal, tk=Tk, block_q=bq, block_k=bk,
            n_heads=Hq,
        ),
        grid=(B * Hq, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, qi, ki: (kv_from_qrow(bh), ki_live(qi, ki), 0)),
            pl.BlockSpec((1, bk, D), lambda bh, qi, ki: (kv_from_qrow(bh), ki_live(qi, ki), 0)),
            pl.BlockSpec((1, bq, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * Hq, tq_pad, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        # dq accumulates across the (sequential) KV dim; the rest are
        # independent — see the fwd kernel's note on megacore splitting.
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, qp, kp, vp, dop, res_b)

    # ---- dK, dV ----
    def q_from_kvrow(bkh, ki, gq):
        b, hkv = bkh // Hkv, bkh % Hkv
        g = gq // n_q
        return b * Hq + hkv * G + g

    def qi_live(ki, gq):
        return culled_qi(ki, gq % n_q, cull, bq, bk, n_q)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=s, causal=causal, tk=Tk, block_q=bq,
            block_k=bk, n_q=n_q, n_heads=Hkv,
        ),
        grid=(B * Hkv, n_k, G * n_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda bkh, ki, gq: (q_from_kvrow(bkh, ki, gq), qi_live(ki, gq), 0)),
            pl.BlockSpec((1, bk, D), lambda bkh, ki, gq: (bkh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bkh, ki, gq: (bkh, ki, 0)),
            pl.BlockSpec((1, bq, D), lambda bkh, ki, gq: (q_from_kvrow(bkh, ki, gq), qi_live(ki, gq), 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bkh, ki, gq: (q_from_kvrow(bkh, ki, gq), qi_live(ki, gq), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bkh, ki, gq: (bkh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bkh, ki, gq: (bkh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, tk_pad, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hkv, tk_pad, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        # dk/dv accumulate across the (sequential) grouped-Q dim.
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, qp, kp, vp, dop, res_b)

    return (
        dq[:, :Tq].reshape(B, Hq, Tq, D),
        dk[:, :Tk].reshape(B, Hkv, Tk, D),
        dv[:, :Tk].reshape(B, Hkv, Tk, D),
    )
