"""Flash-attention backward: custom VJP with O(T) residual memory.

Autodiff through the blockwise forward would store every block's probability
matrix (O(T²) across the scan). The flash recipe instead saves only
``(q, k, v, out, lse)`` and *recomputes* probabilities blockwise in the
backward — the standard FLOPs-for-HBM trade that suits TPU (SURVEY.md §7
hard part 1).

One subtlety beyond the textbook recipe: this framework's attention returns
``(out, lse)`` and downstream code **differentiates through lse as well** (the
tree merge weighs shards by ``exp(lse - m)``). Since ``∂lse/∂logits`` is the
softmax ``p`` itself, the lse cotangent folds into the standard backward as an
extra additive term in the delta:

    ds = p · (dout·vᵀ − Δ + dlse),   Δ = rowsum(dout ⊙ out)

so supporting it costs nothing.

The custom VJP wraps the *dispatcher* level: the forward runs whichever impl
was requested; the backward matches it — ``impl='pallas'`` runs the Pallas
backward kernels (:mod:`tree_attention_tpu.ops.pallas_bwd`), everything else
runs the blockwise jnp recomputation below. ``_attn_bwd`` is the single
dispatch seam.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tree_attention_tpu.ops.block_utils import matmul_precision, static_offsets
from tree_attention_tpu.ops.reference import (
    NEG_INF,
    attention_blockwise,
    attention_naive,
)


class _Cfg(NamedTuple):
    causal: bool
    scale: Optional[float]
    impl: str
    block_size: int
    block_q: Optional[int] = None  # Pallas fwd Q-tile; None = kernel default
    # Pallas bwd Q-tile; None = block_q. The dispatcher threads a smaller
    # default here (tuning.default_block_q_bwd): the bwd kernels' larger
    # per-tile live state VMEM-OOMs at the fwd-optimal tile. An explicit
    # caller block_q flows to both passes unchanged.
    block_q_bwd: Optional[int] = None
    # Static copies of integer offsets. Residuals flow through custom_vjp as
    # arrays, which would hide compile-time offsets from the backward and
    # silently disable the Pallas kernels' grid-level causal culling; carrying
    # them in the (static) cfg keeps fwd and bwd specialised identically.
    q_off: Optional[int] = None
    kv_off: Optional[int] = None


def _zero_like_offset(x):
    """Cotangent for integer offset args: float0 zeros of matching shape."""
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _attn(cfg: _Cfg, q, k, v, q_offset, kv_offset):
    return _raw_forward(cfg, q, k, v, q_offset, kv_offset)


def _raw_forward(cfg, q, k, v, q_offset, kv_offset):
    if cfg.q_off is not None:
        q_offset, kv_offset = cfg.q_off, cfg.kv_off
    if cfg.impl == "blockwise":
        return attention_blockwise(
            q, k, v, causal=cfg.causal, scale=cfg.scale,
            q_offset=q_offset, kv_offset=kv_offset, block_size=cfg.block_size,
        )
    if cfg.impl == "naive":
        return attention_naive(
            q, k, v, causal=cfg.causal, scale=cfg.scale,
            q_offset=q_offset, kv_offset=kv_offset,
        )
    if cfg.impl == "pallas":
        from tree_attention_tpu.ops.pallas_attention import attention_pallas_fwd

        kw = {} if cfg.block_q is None else {"block_q": cfg.block_q}
        return attention_pallas_fwd(
            q, k, v, causal=cfg.causal, scale=cfg.scale,
            q_offset=q_offset, kv_offset=kv_offset, block_size=cfg.block_size,
            **kw,
        )
    if cfg.impl == "pallas_decode":
        # Decode-shaped forward; its backward runs the blockwise jnp
        # recomputation (decode grads are rare and Tq is tiny there).
        from tree_attention_tpu.ops.pallas_decode import attention_pallas_decode

        return attention_pallas_decode(
            q, k, v, causal=cfg.causal, scale=cfg.scale,
            q_offset=q_offset, kv_offset=kv_offset, block_size=cfg.block_size,
        )
    raise ValueError(f"unknown impl {cfg.impl!r}")


def _attn_fwd(cfg, q, k, v, q_offset, kv_offset):
    out, lse = _raw_forward(cfg, q, k, v, q_offset, kv_offset)
    return (out, lse), (q, k, v, out, lse, q_offset, kv_offset)


def _attn_bwd(cfg, residuals, cotangents):
    q, k, v, out, lse, q_offset, kv_offset = residuals
    if cfg.q_off is not None:
        q_offset, kv_offset = cfg.q_off, cfg.kv_off
    dout, dlse = cotangents
    if cfg.impl == "pallas":
        from tree_attention_tpu.ops.pallas_bwd import attention_bwd_pallas

        bwd = attention_bwd_pallas
        bq = cfg.block_q if cfg.block_q_bwd is None else cfg.block_q_bwd
        kw = {} if bq is None else {"block_q": bq}
    else:
        bwd = attention_bwd_blockwise
        kw = {}
    dq, dk, dv = bwd(
        q, k, v, out, lse, dout, dlse,
        causal=cfg.causal, scale=cfg.scale,
        q_offset=q_offset, kv_offset=kv_offset, block_size=cfg.block_size,
        **kw,
    )
    return dq, dk, dv, _zero_like_offset(q_offset), _zero_like_offset(kv_offset)


_attn.defvjp(_attn_fwd, _attn_bwd)


def flash_attention_vjp(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    impl: str = "blockwise",
    block_size: int = 512,
    block_q: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Differentiable attention with the flash (recompute) backward."""
    q_off = kv_off = None
    if static_offsets(q_offset, kv_offset):
        q_off, kv_off = int(q_offset), int(kv_offset)
    cfg = _Cfg(
        causal=causal, scale=scale, impl=impl, block_size=block_size,
        block_q=block_q, block_q_bwd=block_q_bwd, q_off=q_off, kv_off=kv_off,
    )
    return _attn(cfg, q, k, v, q_offset, kv_offset)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_size")
)
def attention_bwd_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    dlse: jax.Array,
    *,
    causal: bool,
    scale: Optional[float],
    q_offset,
    kv_offset,
    block_size: int = 512,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Blockwise jnp flash backward: recompute p from (q, k, lse) per block.

    Grouped-query aware: dk/dv are reduced over the query-head group axis, so
    KV (and their grads) stay ``Hkv``-sized throughout.
    """
    B, Hq, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    G = Hq // Hkv
    s = (D ** -0.5) if scale is None else scale

    if Tk == 0:
        return jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v)

    from tree_attention_tpu.ops.block_utils import split_kv_blocks, tile_mask

    qf = q.astype(jnp.float32).reshape(B, Hkv, G, Tq, D)
    doutf = dout.astype(jnp.float32).reshape(B, Hkv, G, Tq, D)
    outf = out.astype(jnp.float32).reshape(B, Hkv, G, Tq, D)
    lse_g = lse.reshape(B, Hkv, G, Tq)
    dlse_g = dlse.astype(jnp.float32).reshape(B, Hkv, G, Tq)
    # Fully-masked rows have lse = -inf; exp(logits - 0) with logits = -inf
    # still gives p = 0, which is the correct (vanishing) gradient.
    lse_safe = jnp.where(jnp.isneginf(lse_g), 0.0, lse_g)

    # Δ folded with the lse cotangent (see module docstring).
    delta = jnp.sum(doutf * outf, axis=-1) - dlse_g  # (B, Hkv, G, Tq)

    kb, vb, num_blocks, blk = split_kv_blocks(k, v, block_size)

    def compute(dq_acc, inputs):
        blk_idx, k_blk, v_blk = inputs
        kf = k_blk.astype(jnp.float32)
        vf = v_blk.astype(jnp.float32)
        logits = jnp.einsum(
            "bhgqd,bhkd->bhgqk", qf, kf, preferred_element_type=jnp.float32,
            precision=matmul_precision(jnp.float32),
        ) * s
        valid = tile_mask(Tq, blk, blk_idx, Tk, q_offset, kv_offset, causal)
        logits = jnp.where(valid[None, None, None], logits, NEG_INF)

        p = jnp.exp(logits - lse_safe[..., None])  # (B,Hkv,G,Tq,blk)
        dp = jnp.einsum("bhgqd,bhkd->bhgqk", doutf, vf,
                        precision=matmul_precision(jnp.float32))
        ds = p * (dp - delta[..., None])  # lse cotangent already folded in

        dq_blk = jnp.einsum("bhgqk,bhkd->bhgqd", ds, kf,
                            precision=matmul_precision(jnp.float32)) * s
        dk_blk = jnp.einsum("bhgqk,bhgqd->bhkd", ds, qf,
                            precision=matmul_precision(jnp.float32)) * s
        dv_blk = jnp.einsum("bhgqk,bhgqd->bhkd", p, doutf,
                            precision=matmul_precision(jnp.float32))
        return dq_acc + dq_blk, (dk_blk, dv_blk)

    def skip(dq_acc, inputs):
        _, k_blk, v_blk = inputs
        zero = jnp.zeros((B, Hkv, k_blk.shape[2], D), jnp.float32)
        return dq_acc, (zero, zero)

    def body(dq_acc, inputs):
        if not causal:
            return compute(dq_acc, inputs)
        # Same live-tile cull as the forward: fully-masked blocks have p = 0
        # everywhere, hence zero dk/dv and no dq contribution.
        blk_idx = inputs[0]
        live = (q_offset + Tq - 1) >= (kv_offset + blk_idx * blk)
        return lax.cond(live, compute, skip, dq_acc, inputs)

    idxs = jnp.arange(num_blocks)
    dq0 = jnp.zeros((B, Hkv, G, Tq, D), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(body, dq0, (idxs, kb, vb))

    dk = dk_blocks.transpose(1, 2, 0, 3, 4).reshape(B, Hkv, num_blocks * blk, D)
    dv = dv_blocks.transpose(1, 2, 0, 3, 4).reshape(B, Hkv, num_blocks * blk, D)
    dk = dk[:, :, :Tk]
    dv = dv[:, :, :Tk]
    return (
        dq.reshape(B, Hq, Tq, D).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )
