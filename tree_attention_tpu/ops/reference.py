"""Pure-jnp reference attention kernels emitting ``(out, lse)``.

These are the numerics anchor of the framework and the CPU fallback path. The
kernel contract — every attention impl returns the attention output *and* the
logsumexp of the scaled logits per query row — is the spine of the tree merge,
mirroring the reference's ``flash_res_lse`` (``/root/reference/model.py:60-83``)
but fixing its three confirmed bugs:

1. The contraction runs over the *sequence* axis (the reference's layout
   mismatch made it attend over the head axis, ``model.py:74`` with
   ``model.py:51-53`` layouts).
2. ``lse`` is the logsumexp of the **scaled logits**, not of post-softmax
   probabilities (``model.py:80``), which is what the safe-softmax merge
   requires.
3. Causal masking uses ``-inf`` before the softmax, not ``tril`` zeroing
   (``model.py:76``), and supports cross-shard offsets so a sequence-sharded
   KV block knows its global position.

Two implementations share one contract:

- :func:`attention_naive` — materialises the score matrix; the readable
  oracle for tests (small shapes only).
- :func:`attention_blockwise` — ``lax.scan`` over KV blocks with an online
  softmax (running max / sum / accumulator), O(block) memory; the
  any-backend fallback with the same access pattern as the Pallas kernel.

Shapes (TPU-friendly, head-major so the trailing two dims tile onto the MXU):

- ``q``: ``(B, Hq, Tq, D)``
- ``k``, ``v``: ``(B, Hkv, Tk, D)`` with ``Hq % Hkv == 0`` (GQA/MQA)
- returns ``out``: ``(B, Hq, Tq, D)`` (q's dtype), ``lse``: ``(B, Hq, Tq)``
  float32.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tree_attention_tpu.ops.block_utils import (  # noqa: F401  (canonical home)
    NEG_INF,
    matmul_precision,
)


def _default_scale(head_dim: int, scale: Optional[float]) -> float:
    return (head_dim ** -0.5) if scale is None else scale


def _causal_mask(
    q_len: int, k_len: int, q_offset, k_offset
) -> jax.Array:
    """Visibility mask: query at global position i sees key at global j iff i >= j.

    ``q_offset``/``k_offset`` are the global positions of the first local
    query/key row — this is how a sequence-sharded KV block expresses causality
    against replicated or sharded Q (the reference never faced this: its causal
    path is dead code, ``model.py:100``).
    """
    q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
    k_pos = k_offset + lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
    return q_pos >= k_pos


def finalize(out_unnormalized: jax.Array, m: jax.Array, l: jax.Array, out_dtype) -> Tuple[jax.Array, jax.Array]:
    """Turn running (acc, max, sum) online-softmax state into (out, lse).

    Rows that saw no visible key (``m == -inf`` / ``l == 0``) produce zero
    output and ``lse == -inf`` so a later :func:`merge_partials` treats the
    shard as contributing nothing — the identity of the safe-softmax monoid.
    """
    empty = l <= 0.0
    safe_l = jnp.where(empty, 1.0, l)
    out = out_unnormalized / safe_l[..., None]
    out = jnp.where(empty[..., None], 0.0, out)
    lse = jnp.where(empty, NEG_INF, m + jnp.log(safe_l))
    return out.astype(out_dtype), lse.astype(jnp.float32)


def attention_naive(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    tree_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Materialised-scores attention. Oracle implementation for tests.

    GQA is grouped, not expanded: query head ``h`` reads KV head ``h // G``
    through a reshape (``(B, Hkv, G, Tq, D)``) and grouped einsums, so KV is
    never replicated in memory — the same mapping the Pallas kernel's
    BlockSpec index does in VMEM. That keeps this path viable for big GQA
    decode caches, not just as a test oracle.
    """
    B, Hq, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    s = _default_scale(D, scale)

    if Tk == 0:  # empty shard contributes the safe-softmax identity
        return (
            jnp.zeros_like(q),
            jnp.full((B, Hq, Tq), NEG_INF, jnp.float32),
        )

    qg = q.reshape(B, Hkv, G, Tq, D)
    # See matmul_precision: non-bf16 operands must not be silently lowered
    # to a single bf16 pass (MXU on TPU, and observed on the CPU backend for
    # some contraction layouts) — unacceptable in the oracle; bf16 operands
    # already multiply exactly into f32 and keep the MXU fast path.
    logits = jnp.einsum(
        "bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32,
        precision=matmul_precision(qg.dtype, k.dtype),
    ) * s
    if tree_mask is not None:
        # Tree-window rule (see attention_blockwise): visible below the
        # window, per the packed ancestor mask inside it, never past it.
        if not causal:
            raise ValueError("tree_mask requires causal=True")
        rel = (
            kv_offset + lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
            - q_offset
        )
        taken = jnp.take_along_axis(
            tree_mask,
            jnp.broadcast_to(jnp.clip(rel, 0, Tq - 1)[None], (B, Tq, Tk)),
            axis=2,
        )
        mask = (rel < 0)[None] | ((rel < Tq)[None] & taken)
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    elif causal:
        mask = _causal_mask(Tq, Tk, q_offset, kv_offset)
        logits = jnp.where(mask[None, None, None], logits, NEG_INF)

    m = jnp.max(logits, axis=-1)
    # exp(-inf - -inf) would be nan; fully-masked rows get m := 0 so that
    # exp(-inf - 0) = 0 and the row drops out cleanly.
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    # The value contraction runs in full f32 (p carries real f32 precision
    # from the exp) — this is the oracle; perf paths do the FA2 p-downcast
    # trick instead.
    acc = jnp.einsum(
        "bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32),
        precision=matmul_precision(jnp.float32),
    )
    return finalize(
        acc.reshape(B, Hq, Tq, D),
        m.reshape(B, Hq, Tq),
        l.reshape(B, Hq, Tq),
        q.dtype,
    )


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_size"))
def attention_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    block_size: int = 512,
    tree_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Online-softmax attention: ``lax.scan`` over KV blocks, O(block) memory.

    Same math the Pallas kernel performs on-chip; usable on any backend. This
    is what the reference's ``flash_res_lse`` *claims* to be ("simulates flash
    attention", ``model.py:62``) but isn't — it materialises the full score
    matrix.

    GQA runs against *unexpanded* KV: query heads are folded into a group axis
    (``bghqd,bhkd->bghqk``) so KV memory stays ``Hkv``-sized — the point of
    grouped-query attention for big KV caches.

    ``tree_mask`` (a ``(B, Tq, Tq)`` bool array, requires ``causal=True``
    and a scalar ``q_offset``) switches the **window rule** of speculative
    tree verification (SpecInfer, arXiv:2305.09781) on: the Tq query rows
    are packed draft-tree nodes occupying KV positions ``[q_offset,
    q_offset + Tq)``, and query row ``i`` sees KV position ``p`` iff
    ``p < q_offset`` (the committed history) or ``p`` lies in the window
    with ``tree_mask[b, i, p - q_offset]`` set (an ancestor of ``i`` — or
    ``i`` itself). A lower-triangular mask reproduces plain causal
    masking bit-for-bit (same visibility sets, same arithmetic).
    """
    B, Hq, Tq, D = q.shape
    Hkv = k.shape[1]
    if Hq % Hkv != 0:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    Tk = k.shape[2]
    s = _default_scale(D, scale)
    if tree_mask is not None:
        if not causal:
            raise ValueError("tree_mask requires causal=True")
        if tree_mask.shape != (B, Tq, Tq):
            raise ValueError(
                f"tree_mask must be (B, Tq, Tq) = {(B, Tq, Tq)}, got "
                f"{tree_mask.shape}"
            )

    if Tk == 0:  # empty shard contributes the safe-softmax identity
        return (
            jnp.zeros_like(q),
            jnp.full((B, Hq, Tq), NEG_INF, jnp.float32),
        )

    from tree_attention_tpu.ops.block_utils import split_kv_blocks, tile_mask

    qf = (q.astype(jnp.float32) * s).reshape(B, Hkv, G, Tq, D)
    kb, vb, num_blocks, blk = split_kv_blocks(k, v, block_size)

    m0 = jnp.full((B, Hkv, G, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Tq), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, G, Tq, D), jnp.float32)

    def compute(carry, inputs):
        m_prev, l_prev, acc = carry
        blk_idx, k_blk, v_blk = inputs
        logits = jnp.einsum(
            "bhgqd,bhkd->bhgqk", qf, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(jnp.float32),
        )
        if tree_mask is None:
            valid = tile_mask(Tq, blk, blk_idx, Tk, q_offset, kv_offset,
                              causal)
            logits = jnp.where(valid[None, None, None], logits, NEG_INF)
        else:
            # Tree-window rule: below the window everything is visible,
            # inside it the packed ancestor mask decides, past it nothing
            # is (the plain causal rule is the lower-triangular special
            # case). ``rel`` is the KV position relative to the window
            # start q_offset.
            col = blk_idx * blk + lax.broadcasted_iota(
                jnp.int32, (Tq, blk), 1
            )
            rel = kv_offset + col - q_offset  # (Tq, blk)
            taken = jnp.take_along_axis(
                tree_mask,
                jnp.broadcast_to(
                    jnp.clip(rel, 0, Tq - 1)[None], (B, Tq, blk)
                ),
                axis=2,
            )
            valid = (col < Tk)[None] & (
                (rel < 0)[None] | ((rel < Tq)[None] & taken)
            )
            logits = jnp.where(valid[:, None, None], logits, NEG_INF)

        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m_prev), NEG_INF, m_prev - m_safe))
        p = jnp.exp(logits - m_safe[..., None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p, v_blk.astype(jnp.float32),
            precision=matmul_precision(jnp.float32),
        )
        return m_new, l_new, acc_new

    def body(carry, inputs):
        if not causal:
            return compute(carry, inputs), None
        # Skip fully-masked blocks: a block is live iff its most visible
        # pairing (last query row, first key column) is unmasked. This makes
        # causal work proportional to live tiles — the property the zigzag
        # layout balances across shards (the Pallas kernels skip via
        # pl.when; this is the same cull for the jnp fallback).
        blk_idx = inputs[0]
        live = (q_offset + Tq - 1) >= (kv_offset + blk_idx * blk)
        return lax.cond(live, compute, lambda c, _: c, carry, inputs), None

    idxs = jnp.arange(num_blocks)
    (m, l, acc), _ = lax.scan(body, (m0, l0, acc0), (idxs, kb, vb))
    out, lse = finalize(acc, m, l, q.dtype)
    return out.reshape(B, Hq, Tq, D), lse.reshape(B, Hq, Tq)


def merge_partials(outs: jax.Array, lses: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Merge per-shard ``(out, lse)`` partials along a leading stacked axis.

    The local-device form of the tree reduction: given ``outs`` of shape
    ``(S, ..., D)`` and ``lses`` of shape ``(S, ...)`` from S KV shards,
    recombine into the exact global softmax via the safe-softmax monoid:
    ``m = max_i lse_i; num = Σ out_i · e^{lse_i − m}; den = Σ e^{lse_i − m}``.

    This is what the reference's three allreduces compute across ranks
    (``model.py:108,114-115``) — here as a pure function, reusable both in
    tests and inside the split-KV decode kernel.
    """
    m = jnp.max(lses, axis=0)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w = jnp.exp(lses - m_safe[None])
    den = jnp.sum(w, axis=0)
    num = jnp.sum(outs.astype(jnp.float32) * w[..., None], axis=0)
    return finalize_merge(num, den, m, outs.dtype)


def finalize_merge(
    num: jax.Array, den: jax.Array, m: jax.Array, out_dtype
) -> Tuple[jax.Array, jax.Array]:
    """Normalise reduced safe-softmax state into ``(out, lse)``.

    The ONE definition of the merge epilogue — rows with no visible keys
    (``den <= 0``) emit 0 / −inf — shared by :func:`merge_partials`, the
    tree merge (``parallel/tree.py``), and both ring paths
    (``parallel/ring.py``), so the families' numerics cannot diverge.
    """
    empty = den <= 0.0
    den_safe = jnp.where(empty, 1.0, den)
    out = jnp.where(empty[..., None], 0.0, num / den_safe[..., None])
    lse = jnp.where(empty, NEG_INF, m + jnp.log(den_safe))
    return out.astype(out_dtype), lse.astype(jnp.float32)
