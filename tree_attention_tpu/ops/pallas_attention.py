"""Pallas TPU flash-attention forward kernel emitting ``(out, lse)``.

This is the real version of what the reference's ``flash_res_lse`` only
simulates (``/root/reference/model.py:60-83`` materialises the full score
matrix; its README TODO at ``README.md:21`` admits flash attention was never
integrated). Here the score matrix never exists: the kernel streams KV tiles
through VMEM, maintains the online-softmax state ``(m, l, acc)`` in scratch
across the (sequential) KV grid dimension, and writes ``out = acc/l`` and
``lse = m + log l`` once per Q tile.

TPU mapping:

- Both matmuls (QKᵀ and P·V) hit the MXU with ``preferred_element_type=f32``;
  tiles default to 128×512×head_dim.
- Grid ``(B·Hq, Tq/bq, Tk/bk)``; the last dim iterates sequentially on TPU,
  which is what lets scratch carry the running softmax state.
- GQA is native: the K/V BlockSpec index map folds the query head down to its
  KV head (no KV replication in HBM or VMEM).
- Causal shard offsets arrive via SMEM scalars (they are traced values inside
  ``shard_map``); fully-masked causal tiles skip both matmuls via ``pl.when``.
- ``interpret=True`` runs the same kernel on CPU for cluster-free tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tree_attention_tpu.ops.block_utils import (
    LANES as _LANES,
    NEG_INF,
    culled_ki,
    mask_scores,
    matmul_precision,
    static_offsets,
    tile_live,
    tpu_compiler_params,
)


def _lane_bcast(x, n):
    """Widen a lane-replicated ``(bq, LANES)`` state vector to ``(bq, n)``.

    Every lane of ``x`` holds the same value, so slicing narrows and tiling
    widens without changing semantics. The multiple-of-LANES paths stay
    lane-aligned on the VPU; the ``n < LANES`` / non-multiple paths still
    produce a sub-128-lane vector and pay its relayout (reachable only
    with narrow heads or sub-128 test tiles, not the product shapes)."""
    L = x.shape[-1]
    if n == L:
        return x
    if n < L:
        return x[:, :n]
    if n % L == 0:
        return jnp.tile(x, (1, n // L))
    return jnp.tile(x, (1, -(-n // L)))[:, :n]


def _flash_fwd_kernel(
    offs_ref,  # SMEM (2, B): per-batch [q_offset | kv_offset] columns —
               # ragged prefill gives every batch row its own global position
    q_ref,     # VMEM (1, bq, D)
    k_ref,     # VMEM (1, bk, D)
    v_ref,     # VMEM (1, bk, D)
    out_ref,   # VMEM (1, bq, D)
    lse_ref,   # VMEM (1, bq, LANES) — lse broadcast across lanes (TPU tiling
               # requires a 128-multiple trailing dim; host slices lane 0)
    m_scr,     # VMEM (bq, LANES) f32
    l_scr,     # VMEM (bq, LANES) f32
    acc_scr,   # VMEM (bq, D) f32
    *,
    scale: float,
    causal: bool,
    tk: int,
    block_q: int,
    block_k: int,
    n_q_heads: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    b = pl.program_id(0) // n_q_heads  # grid dim 0 runs over B·Hq
    q_offset = offs_ref[0, b]
    kv_offset = offs_ref[1, b]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(tile_live(qi, ki, block_q, block_k, q_offset, kv_offset, causal))
    def _compute():
        # Operands stay in their native dtype (bf16 hits the MXU's fast
        # path; casting to f32 first would quarter matmul throughput) with
        # f32 accumulation via preferred_element_type.
        s = lax.dot_general(
            q_ref[0],
            k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(q_ref.dtype, k_ref.dtype),
        ) * scale  # (bq, bk) f32

        # Ragged-tail + causal masking; interior tiles skip it entirely.
        s = mask_scores(
            s, qi, ki, block_q, block_k, q_offset, kv_offset, tk, causal
        )

        # Softmax state math stays LANE-REPLICATED at (bq, LANES)
        # throughout: narrow (bq, 1) intermediates force a VPU lane
        # relayout per op, and with ~8 state ops per KV step that overhead
        # measured ~19% of step time at 512/1024 tiles (44.0% -> 54.0%
        # MFU, r5 race vs the JAX-bundled kernel, which keeps state at
        # (bq, 128) for the same reason). Two narrow (bq, 1) reductions
        # necessarily remain — the row max and the row sum of p — each
        # broadcast back to lane width once.
        m_prev = m_scr[...]  # (bq, LANES)
        l_prev = l_scr[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_blk)          # (bq, LANES)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        alpha = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_safe))
        p = jnp.exp(s - _lane_bcast(m_safe, s.shape[-1]))  # masked cols -> 0
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # P is cast to V's dtype for the second MXU matmul (the FA2 trick:
        # probabilities are in [0,1] so bf16 relative error stays small) and
        # accumulated in f32. When Tk is ragged the last tile's trailing V
        # rows are unspecified garbage (no host padding; interpret mode
        # NaN-poisons them) — p's masked columns are exactly 0, but 0·NaN is
        # NaN, so those rows must be zeroed. Static no-op for divisible Tk.
        v_tile = v_ref[0]
        if tk % block_k:
            row_ok = (
                ki * block_k
                + lax.broadcasted_iota(jnp.int32, v_tile.shape, 0)
            ) < tk
            v_tile = jnp.where(row_ok, v_tile, 0)
        acc_scr[...] = acc_scr[...] * _lane_bcast(
            alpha, acc_scr.shape[-1]
        ) + lax.dot_general(
            p.astype(v_ref.dtype), v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(v_ref.dtype, v_ref.dtype),
        )
        m_scr[...] = m_new
        l_scr[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        m = m_scr[...]  # (bq, LANES), lane-replicated
        l = l_scr[...]
        empty = l <= 0.0
        l_safe = jnp.where(empty, 1.0, l)
        D_acc = acc_scr.shape[-1]
        out_ref[0] = (
            jnp.where(
                _lane_bcast(empty, D_acc), 0.0,
                acc_scr[...] / _lane_bcast(l_safe, D_acc),
            )
        ).astype(out_ref.dtype)
        lse = jnp.where(
            empty, NEG_INF, jnp.where(m == NEG_INF, 0.0, m) + jnp.log(l_safe)
        )
        lse_ref[0] = lse




def attention_pallas_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    block_size: int = 512,
    block_q: int = 256,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Raw (non-differentiable) Pallas forward. Same contract as the jnp impls.

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere —
    the same kernel code path is what CI exercises on CPU.

    When ``causal`` and both offsets are compile-time integers (the unsharded
    path), causally dead KV tiles are culled at the grid level: their index
    maps repeat the last live block, so the pipeline elides the DMA — up to
    ~2× less HBM traffic for the bottom-right-aligned training shape. Traced
    offsets (``shard_map``) keep the ``pl.when`` compute skip only. Offsets
    become part of the compile key only in the static case, so a loop over
    *varying* integer offsets should pass them as arrays.

    ``q_offset`` / ``kv_offset`` may also be ``(B,)`` vectors (the ragged
    prefill shape: each batch row is a cache slot at its own position);
    per-batch offsets ride SMEM like the decode kernel's, with the
    ``pl.when`` compute skip per batch row (no grid culling — the grid is
    shared across rows).
    """
    cull = (
        (int(q_offset), int(kv_offset))
        if causal and static_offsets(q_offset, kv_offset)
        else None
    )
    return _attention_pallas_fwd(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset,
        kv_offset=kv_offset, block_size=block_size, block_q=block_q,
        interpret=interpret, cull=cull,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_size", "block_q", "interpret", "cull"
    ),
)
def _attention_pallas_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    scale: Optional[float],
    q_offset,
    kv_offset,
    block_size: int,
    block_q: int,
    interpret: Optional[bool],
    cull: Optional[Tuple[int, int]],
) -> Tuple[jax.Array, jax.Array]:
    B, Hq, Tq, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    s = (D ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if Tk == 0:
        return jnp.zeros_like(q), jnp.full((B, Hq, Tq), NEG_INF, jnp.float32)

    bq = min(block_q, max(Tq, 8))
    bk = min(block_size, max(Tk, _LANES))

    # No host-side padding: Pallas handles ragged last blocks itself, and an
    # explicit jnp.pad copies the ENTIRE Q/K/V every call whenever the length
    # is not a block multiple (measured as the difference between 27% and 92%
    # of HBM roofline on the 64000-token decode; same physics here).
    qp = q.reshape(B * Hq, Tq, D)
    kp = k.reshape(B * Hkv, Tk, D)
    vp = v.reshape(B * Hkv, Tk, D)
    n_q, n_k = -(-Tq // bq), -(-Tk // bk)
    tq_pad = n_q * bq

    from tree_attention_tpu.ops.block_utils import offsets_smem

    offs = offsets_smem(q_offset, kv_offset, B)

    grid = (B * Hq, n_q, n_k)

    def kv_index(bh, qi, ki):
        b, hq = bh // Hq, bh % Hq
        return (b * Hkv + hq // G, culled_ki(qi, ki, cull, bq, bk, n_k), 0)

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel,
            scale=s, causal=causal, tk=Tk, block_q=bq, block_k=bk,
            n_q_heads=Hq,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, D), kv_index),
            pl.BlockSpec((1, bk, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hq, tq_pad, D), q.dtype),
            jax.ShapeDtypeStruct((B * Hq, tq_pad, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        # Batch-head and Q-tile dims are independent; only the KV dim is
        # sequential (scratch carries the online-softmax state across it).
        # Declaring that lets Mosaic split the parallel dims across cores on
        # megacore parts (v5p/v4); no-op on single-core chips (v5e).
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, qp, kp, vp)

    out = out[:, :Tq].reshape(B, Hq, Tq, D)
    lse = lse[:, :Tq, 0].reshape(B, Hq, Tq)
    return out, lse
