"""Split-KV flash decode: the q_len≈1 inference shape, parallelised over KV.

Decode is the reference's entire workload (``/root/reference/model.py:140-145``:
one query token against a 64k-token KV), and it is the shape where a plain
blockwise scan is weakest on TPU: with Tq=1 each KV block contributes one tiny
matvec, and a sequential ``lax.scan`` over blocks serialises what is really a
bandwidth-bound reduction. The standard fix (flash-decode / split-KV) is to
cut KV into S independent chunks, compute per-chunk partial ``(out, lse)`` in
parallel — XLA maps the ``vmap`` over chunks onto parallel work — and combine
with the same safe-softmax monoid the tree reduction uses
(:func:`~tree_attention_tpu.ops.reference.merge_partials`). The split is the
single-device mirror of the cross-device tree merge: same math, chunks instead
of mesh shards.

Masking is uniformly causal-with-offsets: a query at global position
``q_position + i`` sees keys at global positions ``<= q_position + i``. A
padded or partially-filled KV buffer (a cache of capacity Tmax holding
``length`` valid tokens) needs no separate length mask — pass
``q_position = length - Tq`` and every slot ``>= length`` is in the masked
future.
"""

from __future__ import annotations

import numbers
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tree_attention_tpu import obs
from tree_attention_tpu.ops.block_utils import pad_to_block
from tree_attention_tpu.ops.reference import attention_blockwise, merge_partials

# Read once at import: this gate sits on the per-layer hot dispatch path of
# every decode step (a scan body traces it L times per compile, and eager
# callers hit it per call); the env var is a process-level opt-out, not a
# runtime toggle — flipping it after import is not supported here (the
# already-jitted callers it would need to invalidate cannot see an env flip
# anyway; ops/__init__.flash_attention keeps per-call reads for its
# eager-auto path).
_AUTO_PALLAS = os.environ.get("TREE_ATTN_AUTO_PALLAS", "1") != "0"

# Dispatch accounting (trace-time under an enclosing jit — see
# obs.metrics): which decode path served the call, and how many KV/query
# tokens one executed step of it scans/produces. Execution-true token
# totals live in the host loops (bench/harness.py, cli.py).
_DECODE_DISPATCH = obs.counter(
    "decode_dispatch_total",
    "flash_decode dispatches by kernel path (trace-time under jit)",
    labels=("path",),
)
_DECODE_KV_TOKENS = obs.counter(
    "decode_dispatch_kv_tokens_total",
    "KV tokens one executed step of each dispatched decode call scans "
    "(trace-time under jit)",
    labels=("path",),
)


def _account_dispatch(path: str, kv_tokens: int) -> None:
    if not obs.REGISTRY.enabled:
        return
    _DECODE_DISPATCH.labels(path=path).inc()
    _DECODE_KV_TOKENS.labels(path=path).inc(int(kv_tokens))


def default_num_splits(kv_len: int, block_size: int) -> int:
    """Enough chunks to expose parallelism, never smaller than one block.

    The cap scales with context: a flat 16 under-parallelises the
    chunked-vmap path at 256k+ tokens (16 chunks of 16k+ each serialise
    inside one ``lax.scan`` apiece), so beyond 256k tokens the cap grows
    linearly — one extra chunk per 16k tokens — while short contexts keep
    the measured 16-way default.
    """
    cap = max(16, kv_len // 16384)
    return max(1, min(cap, kv_len // max(block_size, 1)))


def gather_paged_kv(
    k: jax.Array, v: jax.Array, block_table: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Materialise the logical ``(B, Hkv, NB·block, D)`` view of a paged
    pool: row ``b``'s logical block ``j`` is pool row ``block_table[b, j]``.

    The eager reference of the block-table kernels — the Pallas paged
    path streams exactly these rows in exactly this order through its
    index maps, so "gather then run the contiguous path" and "run the
    paged kernel" are bit-identical by construction (the oracle the
    randomized block-table tests pin). Out-of-range entries clamp (the
    engine keeps unmapped entries at 0; clamped garbage is causally
    masked either way)."""

    def g(pool: jax.Array) -> jax.Array:
        B, NB = block_table.shape
        N, Hkv, blk, D = pool.shape
        idx = jnp.clip(block_table, 0, N - 1)
        rows = jnp.moveaxis(pool[idx], 1, 2)  # (B, Hkv, NB, blk, D)
        return rows.reshape(B, Hkv, NB * blk, D)

    return g(k), g(v)


def flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_position=None,
    scale: Optional[float] = None,
    num_splits: Optional[int] = None,
    block_size: Optional[int] = None,
    block_table: Optional[jax.Array] = None,
    tree_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Causal decode attention of a few new queries against a long KV buffer.

    Args:
      q: ``(B, Hq, Tq, D)`` — the new tokens' queries (Tq is typically 1;
        a few for speculative/chunked decode).
      k, v: ``(B, Hkv, Tk, D)`` KV buffer; only positions ``<= q_position + i``
        are visible to query ``i``, so a cache longer than the valid prefix is
        handled by ``q_position`` alone.
      q_position: global position of the first query row. Defaults to
        ``Tk - Tq`` (queries are the newest tokens of a fully-valid buffer).
        May be a traced scalar — decode steps jit once and run at every
        sequence length — or a ``(B,)`` vector for a **ragged batch**:
        each batch row is a cache slot with its own filled length, and the
        causal rule masks every row's unwritten tail independently (slot
        ``i``'s query sits at ``q_position[i]``; everything beyond is its
        masked future).
      num_splits: KV chunks computed in parallel on the chunked-vmap (CPU)
        path; default scales with ``Tk / block_size``, capped at
        ``max(16, Tk // 16384)`` (see :func:`default_num_splits` — the cap
        grows with context so 256k+ buffers keep exposing parallelism).
        The TPU Pallas kernel is split-KV internally (one chunk per
        ``block_size`` KV tile), so this knob is inert there.
      block_size: KV tile length. ``None`` picks the impl-appropriate
        default (the measured :mod:`~tree_attention_tpu.ops.tuning` table
        for the flash-decode kernel, 512 for the Q-tiled prefill kernel and
        the chunked path); an explicit value is honored as given everywhere.

    Returns:
      ``(out, lse)``: ``(B, Hq, Tq, D)`` in q's dtype, ``(B, Hq, Tq)`` float32.

    With ``block_table`` the buffer is **paged** (``k``/``v`` are
    ``(N, Hkv, block, D)`` pools, see
    :class:`~tree_attention_tpu.models.decode.PagedKVCache`): on the TPU
    decode-kernel path the table rides scalar prefetch into the Pallas
    kernel (no gather); everywhere else — the chunked-vmap CPU path and
    prefill-sized Tq on the Q-tiled kernel — the logical view is
    gathered once via :func:`gather_paged_kv` and the contiguous path
    runs unchanged, which keeps eager and Pallas bit-exact.

    ``tree_mask`` (a ``(B, Tq, Tq)`` bool array; requires a ``(B,)``
    ``q_position`` and ``Tq <= 32``) switches on the speculative
    tree-verification window rule (SpecInfer, arXiv:2305.09781): the Tq
    query rows are packed draft-tree nodes occupying KV positions
    ``[q_position[b], q_position[b] + Tq)`` of their slot, and row ``i``
    sees a window position ``j`` iff ``tree_mask[b, i, j]`` (its
    ancestors and itself); everything below the window stays visible,
    everything past it masked. A lower-triangular mask IS the plain
    causal rule, bit-for-bit. Supported on the chunked-vmap path and the
    Pallas decode kernels (as a packed bitmask in SMEM-adjacent VMEM
    lanes); the Q-tiled prefill kernel never sees spec-sized Tq.
    """
    B, Hq, Tq, D = q.shape
    if tree_mask is not None:
        if Tq > 32:
            raise ValueError(
                f"tree_mask packs ancestor sets into int32 bitmasks: "
                f"Tq={Tq} exceeds 32"
            )
        if getattr(q_position, "ndim", 0) != 1:
            raise ValueError(
                "tree_mask needs a per-slot (B,) q_position (the window "
                "start is each slot's committed length)"
            )
        if tree_mask.shape != (B, Tq, Tq):
            raise ValueError(
                f"tree_mask must be (B, Tq, Tq) = {(B, Tq, Tq)}, got "
                f"{tree_mask.shape}"
            )
    Tk = (
        block_table.shape[1] * k.shape[2] if block_table is not None
        else k.shape[2]
    )
    if q_position is None:
        if block_table is not None:
            # Defaulting to Tk - Tq would place the queries at the END of
            # the LOGICAL capacity, causally exposing every table entry —
            # including unwritten ones still pointing at block 0 (some
            # other slot's data). Paged callers know their true lengths.
            raise ValueError("paged decode needs an explicit q_position")
        q_position = Tk - Tq
    # Ragged batch: one q_position per batch row (cache slot).
    ragged = getattr(q_position, "ndim", 0) == 1

    # On TPU the Pallas flash-decode kernel subsumes the chunked-vmap form:
    # it is itself split-KV (sequential KV tiles with carried online-softmax
    # state) and streams at the HBM roofline at any context length.
    from tree_attention_tpu.ops import _on_tpu, _pallas_available

    if _AUTO_PALLAS and _on_tpu(q) and _pallas_available():
        # Kernel choice and tile defaults live in ops.tuning (shared with
        # flash_attention's auto gate). Prefill-sized Tq takes the Q-tiled
        # kernel: the decode kernel's group packing would spill into
        # multiple Q tiles, each re-streaming the whole KV buffer.
        from tree_attention_tpu.ops.tuning import (
            default_block_size,
            tpu_kernel_for,
        )

        impl = tpu_kernel_for(Tq)
        if tree_mask is not None and impl != "pallas_decode":
            # Spec-tree chunks are <= 32 rows, squarely the decode
            # kernel's regime; the Q-tiled kernel has no mask path.
            impl = "pallas_decode"
        if block_table is not None:
            if impl == "pallas_decode":
                from tree_attention_tpu.ops.pallas_decode import (
                    attention_pallas_decode,
                )

                # The paged kernel: table-driven DMA, no gather copy.
                _account_dispatch("paged_decode", Tk)
                return attention_pallas_decode(
                    q, k, v, causal=True, scale=scale,
                    q_offset=q_position, kv_offset=0,
                    block_table=block_table, tree_mask=tree_mask,
                )
            # Prefill-sized Tq rides the Q-tiled kernel, which has no
            # table path — one gather materialises the logical view
            # (amortised over Tq rows of prefill compute).
            k, v = gather_paged_kv(k, v, block_table)
        bk = default_block_size(impl, Tk) if block_size is None else block_size
        # Static int offsets specialise the kernel (grid-level causal cull),
        # which is right for the fixed full-buffer default but would
        # recompile per token if a caller advances q_position as a Python
        # int. Only the default position stays static; any other int is
        # demoted to a traced scalar (one compile, no cull) — callers who
        # decode a growing prefix should pass a traced position anyway
        # (models/decode.py does).
        if (
            isinstance(q_position, numbers.Integral)
            and int(q_position) != Tk - Tq
        ):
            q_position = jnp.asarray(q_position, jnp.int32)
        if impl == "pallas_decode":
            from tree_attention_tpu.ops.pallas_decode import (
                attention_pallas_decode,
            )

            kernel = attention_pallas_decode
        else:
            from tree_attention_tpu.ops.pallas_attention import (
                attention_pallas_fwd,
            )

            kernel = attention_pallas_fwd
        _account_dispatch(impl, Tk)
        # Both kernels take scalar OR (B,) offsets (per-batch SMEM
        # columns), so ragged and uniform batches are one dispatch either
        # way.
        kw = {}
        if impl == "pallas_decode":
            kw["tree_mask"] = tree_mask
        return kernel(
            q, k, v, causal=True, scale=scale,
            q_offset=q_position, kv_offset=0, block_size=bk, **kw,
        )

    if block_table is not None:
        # Eager reference: one gather, then the contiguous chunked path —
        # bit-exact with the paged kernel (see gather_paged_kv).
        k, v = gather_paged_kv(k, v, block_table)

    block_size = 512 if block_size is None else block_size
    S = num_splits if num_splits is not None else default_num_splits(Tk, block_size)
    S = max(1, min(S, Tk))
    chunk = -(-Tk // S)  # ceil

    # Pad to S equal chunks; padded slots sit at global positions >= Tk, in
    # every query's masked future, so the causal mask removes them exactly.
    kp = pad_to_block(k, 2, chunk)
    vp = pad_to_block(v, 2, chunk)
    S = kp.shape[2] // chunk
    kb = kp.reshape(B, k.shape[1], S, chunk, D).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(B, v.shape[1], S, chunk, D).transpose(2, 0, 1, 3, 4)
    offsets = jnp.arange(S) * chunk

    def one_chunk(k_s: jax.Array, v_s: jax.Array, off: jax.Array):
        if ragged:
            # Per-slot offsets: vmap the online-softmax scan over batch so
            # each row masks against its own q_position. Same chunking,
            # same merge — a row's partials are identical to the scalar
            # path's, so ragged and uniform batches agree bit-for-bit.
            # A tree mask rides the same vmap (one (Tq, Tq) ancestor mask
            # per slot, applied against that slot's window offset).
            def per_slot(q_b, k_b, v_b, pos_b, *tm_b):
                o, l = attention_blockwise(
                    q_b[None], k_b[None], v_b[None],
                    causal=True, scale=scale,
                    q_offset=pos_b, kv_offset=off,
                    block_size=min(block_size, chunk),
                    tree_mask=tm_b[0][None] if tm_b else None,
                )
                return o[0], l[0]

            args = (q, k_s, v_s, q_position)
            if tree_mask is not None:
                args = args + (tree_mask,)
            return jax.vmap(per_slot)(*args)
        return attention_blockwise(
            q, k_s, v_s,
            causal=True, scale=scale,
            q_offset=q_position, kv_offset=off,
            block_size=min(block_size, chunk),
        )

    _account_dispatch("chunked_vmap", Tk)
    outs, lses = jax.vmap(one_chunk)(kb, vb, offsets)
    return merge_partials(outs, lses)


def paged_local_partial(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    local_table: jax.Array,
    *,
    q_position,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One shard's flash partial over its LOCAL slice of a sequence-sharded
    paged pool (ISSUE 18): the per-shard half of the tree-attention decode
    monoid, run inside ``shard_map`` by
    :func:`~tree_attention_tpu.parallel.tree.paged_tree_decode`.

    Args:
      q: ``(B, Hq, Tq, D)`` — replicated queries (every shard sees all of
        them; the merge weighs the partials).
      k, v: ``(Nl, Hkv, block, D)`` — this shard's pool slice (``Nl = N/W``
        blocks of the global pool).
      local_table: ``(B, NB)`` int32 — the slot tables rebased to LOCAL
        block ids: entries in ``[0, Nl)`` name a local block, **negative
        entries mean the logical block lives on another shard** and its
        keys must not contribute here (the per-slot cull against the
        shard's local coverage). The signed convention is shared with the
        Pallas local-partial kernel
        (:func:`~tree_attention_tpu.ops.pallas_decode
        .attention_pallas_decode` with ``local_blocks=True``).
      q_position: per-slot ``(B,)`` global position of each slot's first
        query row (the ragged serving shape); the causal rule is the usual
        ``key_pos <= q_position[b] + i`` in LOGICAL positions — a logical
        block's keys sit at the same global positions on every shard, so
        the per-shard partials merge into exactly the replicated result.
      k_scale, v_scale: optional ``(Nl, Hkv)`` per-block int8 scales (the
        slice sharded WITH the pool slice); when given, ``k``/``v`` are
        int8 and each local block's keys/values are dequantized under its
        own scale before the partial — the same quantize-then-dequantize
        rows the replicated off-kernel path attends over.

    Returns:
      ``(out, lse)`` — ``(B, Hq, Tq, D)`` in q's dtype and ``(B, Hq, Tq)``
      float32, normalized WITHIN the shard; rows with no locally visible
      key emit the safe-softmax identity ``(0, -inf)`` (see
      :func:`~tree_attention_tpu.ops.reference.finalize`), so empty or
      fully-future shards drop out of the merge exactly.
    """
    from tree_attention_tpu.ops import _on_tpu, _pallas_available
    from tree_attention_tpu.ops.reference import (
        NEG_INF,
        _default_scale,
        finalize,
        matmul_precision,
    )

    B, Hq, Tq, D = q.shape
    Nl, Hkv, blk, _ = k.shape
    NB = local_table.shape[1]
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if getattr(q_position, "ndim", 0) != 1:
        raise ValueError(
            "paged_local_partial needs a per-slot (B,) q_position"
        )

    if not quant and _AUTO_PALLAS and _on_tpu(q) and _pallas_available():
        from tree_attention_tpu.ops.pallas_decode import (
            attention_pallas_decode,
        )

        _account_dispatch("paged_local_partial", NB * blk)
        return attention_pallas_decode(
            q, k, v, causal=True, scale=scale,
            q_offset=q_position, kv_offset=0,
            block_table=local_table, local_blocks=True,
        )

    # Reference path (CPU / interpret / int8-dequant): gather the local
    # logical view — unowned entries clamp to block 0 and are masked out
    # below, mirroring gather_paged_kv's clamp-then-mask contract.
    owned = local_table >= 0
    idx = jnp.clip(local_table, 0, Nl - 1)

    def view(pool: jax.Array, scl: Optional[jax.Array]) -> jax.Array:
        rows = jnp.moveaxis(pool[idx], 1, 2)  # (B, Hkv, NB, blk, D)
        if scl is not None:
            s = jnp.swapaxes(scl[idx], 1, 2)  # (B, Hkv, NB)
            rows = (
                rows.astype(jnp.float32) * s[..., None, None]
            ).astype(q.dtype)
        return rows.reshape(B, Hkv, NB * blk, D)

    kb = view(k, k_scale)
    vb = view(v, v_scale)

    G = Hq // Hkv
    s = _default_scale(D, scale)
    qg = q.reshape(B, Hkv, G, Tq, D)
    logits = jnp.einsum(
        "bhgqd,bhkd->bhgqk", qg, kb.astype(q.dtype),
        preferred_element_type=jnp.float32,
        precision=matmul_precision(qg.dtype, kb.dtype),
    ) * s
    key_pos = jnp.arange(NB * blk, dtype=jnp.int32)
    q_pos = (
        jnp.asarray(q_position, jnp.int32)[:, None]
        + jnp.arange(Tq, dtype=jnp.int32)[None, :]
    )  # (B, Tq)
    visible = (
        jnp.repeat(owned, blk, axis=1)[:, None, :]          # local coverage
        & (key_pos[None, None, :] <= q_pos[..., None])      # causal
    )  # (B, Tq, K)
    logits = jnp.where(visible[:, None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bhgqk,bhkd->bhgqd", p, vb.astype(jnp.float32),
        precision=matmul_precision(jnp.float32),
    )
    _account_dispatch("paged_local_partial", NB * blk)
    return finalize(
        acc.reshape(B, Hq, Tq, D),
        m.reshape(B, Hq, Tq),
        l.reshape(B, Hq, Tq),
        q.dtype,
    )
