"""Measured per-shape kernel tile sizes (TPU v5e).

The Pallas kernels take ``(block_q, block_k)`` tile sizes; the best choice
depends on the shape class, not the exact shape, so a small measured table
suffices (VERDICT round-1 item 4). ``tools/tune_sweep.py`` regenerates the
measurements on hardware; entries here are its output on the one v5e chip
this repo is benched on. Lookup is by bucket:

- decode (Tq < 128): keyed by context-length bucket. Streaming tiles — the
  only trade-off is fewer grid steps (bigger bk) vs VMEM and ragged-tail
  waste.

- training (Tq >= 128): ``_TRAIN_TILES`` keyed by sequence length, from the
  round-3 ``tools/measure_campaign.py`` sweep (fwd-first, fwd+bwd tiebreak).

Callers pass ``block_size=None`` / ``block_q=None`` end to end to land here;
any explicit value wins unchanged. ``block_q`` is threaded through the
dispatcher and the custom VJP.
"""

from __future__ import annotations

from typing import Optional

# context-length upper bound -> block_k. Measured on v5e (tools/tune_sweep.py
# round 2; tools/experiments_r3.py 2026-07-31): bigger contexts amortise the
# ~360 ns/tile fixed cost over more streaming — 64k MHA measures 92.5% of
# the HBM roofline at bk=4096 vs 89.9% at 2048, and 1M GQA 91.6% at 4096
# with high run variance at 2048. VMEM caps the top end.
_DECODE_BLOCK_K = (
    (16_384, 1024),
    (float("inf"), 4096),
)

# The int8 cache streams half the bytes per tile, so the per-tile fixed cost
# weighs twice as much relative to DMA — the q8 kernel wants tiles ~2x the
# exact path's. Measured 2026-07-31 (64k ctx): 62.2% of the int8 roofline at
# bk=2048, 76.3% at 4096, 85.2% at 8192 (375.9 us = 1.89x the exact path's
# tokens/sec).
_DECODE_BLOCK_K_Q8 = (
    (16_384, 2048),
    (float("inf"), 8192),
)


def decode_block_k(tk: int) -> int:
    """KV tile length for the flash-decode kernel."""
    for bound, bk in _DECODE_BLOCK_K:
        if tk <= bound:
            return bk
    raise AssertionError("unreachable")


def decode_block_k_q8(tk: int) -> int:
    """KV tile length for the int8-cache flash-decode kernel."""
    for bound, bk in _DECODE_BLOCK_K_Q8:
        if tk <= bound:
            return bk
    raise AssertionError("unreachable")


# The one home of the TPU kernel-dispatch policy shared by flash_attention's
# auto gate and flash_decode: which Pallas kernel fits a query count, and
# each impl's default KV tile.
DECODE_KERNEL_MAX_TQ = 128


def tpu_kernel_for(tq: int) -> str:
    """"pallas_decode" below the Q-tile width, "pallas" (Q-tiled) above."""
    return "pallas_decode" if tq < DECODE_KERNEL_MAX_TQ else "pallas"


# (seq-length upper bound, block_q, block_k) for the Q-tiled training
# kernel. Re-measured on v5e 2026-08-01 (tools/ab_fwd_tiles.py, min-stat
# repeated-slope protocol with deflation screens, after the round-5
# lane-replicated-state and prefetch-zero-culling kernel changes made the
# round-3 table stale): (1024, 1024) wins through 32k — 4k fwd+bwd
# 3.29 -> 2.79 ms (1.18x) vs the old (512, 2048) through the product
# default path, 16k fwd+bwd 36.45 -> 35.24 ms, 32k 133.8 -> 132.4 ms —
# and the smaller KV tile halves the backward kernels' VMEM so their Q
# tile can double (see BWD_MAX_TILE_ELEMS below). At 64k the bases tie
# and at 128k the deeper KV tile is ~1% faster (bench train records,
# same day), so the long bucket keeps (1024, 2048). Wall-clock per model
# step is the comparison basis — the launched-tile MFU shrinks with
# finer tiles because less diagonal waste is launched at all. Both
# kernels clamp tiles to the actual shape, so the table is safe for
# short sequences too.
_TRAIN_TILES = (
    (32768, 1024, 1024),
    (float("inf"), 1024, 2048),
)


def _train_tile(t: int):
    for bound, bq, bk in _TRAIN_TILES:
        if t <= bound:
            return bq, bk
    raise AssertionError("unreachable")


# KV block for the XLA blockwise fallback. The _TRAIN_TILES table above was
# measured on the Pallas kernels only; blockwise (a lax.scan over KV chunks,
# any backend) keeps the round-1 default so an unmeasured table change can't
# silently shift its memory/perf profile on CPU/GPU (ADVICE r3).
BLOCKWISE_BLOCK_K = 512


def default_block_size(impl: str, tk: int) -> int:
    if impl == "pallas_decode":
        return decode_block_k(tk)
    if impl == "pallas":
        return _train_tile(tk)[1]
    return BLOCKWISE_BLOCK_K


# VMEM ceiling for the backward kernels' tiles. The bwd kernels hold more
# per-tile live state than the forward (recomputed s/p/ds alongside the
# dq/dkv accumulators), and the dominant term scales with bq*bk:
# (1024, 2048) measures 24.6 MB of scoped VMEM against the v5e's 16 MB
# limit — a compile-time OOM (observed 2026-07-31, T=16384) — while
# (1024, 1024) and (512, 2048) both compile and run (the former measured
# fastest in the 2026-08-01 A/B). The cap is therefore a product bound,
# not a bare block_q bound. Applied only when the tile comes from this
# table's defaults; an explicitly passed block_q always wins unchanged
# (sweeps must measure what they label).
BWD_MAX_TILE_ELEMS = 1024 * 1024
# Largest bwd Q tile ever validated on-chip; the product bound alone
# would allow (2048, 512), which no sweep has measured.
BWD_MAX_BLOCK_Q = 1024


def default_block_q(tq: int, tk: int) -> int:
    """Q-tile length for the Q-tiled Pallas forward kernel."""
    return _train_tile(tq)[0]


def default_block_q_bwd(tq: int, tk: int, block_k: Optional[int] = None) -> int:
    """Q-tile length for the Pallas backward kernels (VMEM-capped).

    ``block_k`` is the RESOLVED KV tile the backward kernels will run
    with (it may be caller-supplied rather than this table's default);
    the cap keeps ``bq * bk`` within the measured VMEM-feasible product.
    The fallback mirrors ``default_block_size("pallas", tk)`` — keyed by
    the KV length, exactly what the dispatcher would resolve — so a
    direct caller that omits ``block_k`` gets a cap consistent with the
    tile the kernels will actually run.
    """
    if block_k is None:
        block_k = _train_tile(tk)[1]
    # No lower floor above the kernels' own minimum (they clamp bq to
    # >= 8): flooring at, say, 128 rows would silently emit a product
    # ABOVE the cap for a huge caller-supplied KV tile (bk=16384 ->
    # 128 * 16384 = 2M elems, the documented compile-OOM class).
    return min(
        default_block_q(tq, tk),
        BWD_MAX_BLOCK_Q,
        max(8, BWD_MAX_TILE_ELEMS // max(block_k, 1)),
    )
