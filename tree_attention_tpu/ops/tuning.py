"""Measured per-shape kernel tile sizes (TPU v5e).

The Pallas kernels take ``(block_q, block_k)`` tile sizes; the best choice
depends on the shape class, not the exact shape, so a small measured table
suffices (VERDICT round-1 item 4). ``tools/tune_sweep.py`` regenerates the
measurements on hardware; entries here are its output on the one v5e chip
this repo is benched on. Lookup is by bucket:

- decode (Tq < 128): keyed by context-length bucket. Streaming tiles — the
  only trade-off is fewer grid steps (bigger bk) vs VMEM and ragged-tail
  waste.

- training (Tq >= 128): ``_TRAIN_TILES`` keyed by sequence length, from the
  round-3 ``tools/measure_campaign.py`` sweep (fwd-first, fwd+bwd tiebreak).

Callers pass ``block_size=None`` / ``block_q=None`` end to end to land here;
any explicit value wins unchanged. ``block_q`` is threaded through the
dispatcher and the custom VJP.
"""

from __future__ import annotations

# context-length upper bound -> block_k. From tools/tune_sweep.py on v5e
# (bigger contexts amortise per-tile cost over more streaming; VMEM caps the
# top end).
_DECODE_BLOCK_K = (
    (16_384, 1024),
    (262_144, 2048),
    (float("inf"), 2048),
)

def decode_block_k(tk: int) -> int:
    """KV tile length for the flash-decode kernel."""
    for bound, bk in _DECODE_BLOCK_K:
        if tk <= bound:
            return bk
    raise AssertionError("unreachable")


# The one home of the TPU kernel-dispatch policy shared by flash_attention's
# auto gate and flash_decode: which Pallas kernel fits a query count, and
# each impl's default KV tile.
DECODE_KERNEL_MAX_TQ = 128


def tpu_kernel_for(tq: int) -> str:
    """"pallas_decode" below the Q-tile width, "pallas" (Q-tiled) above."""
    return "pallas_decode" if tq < DECODE_KERNEL_MAX_TQ else "pallas"


# (seq-length upper bound, block_q, block_k) for the Q-tiled training
# kernel. Measured by tools/measure_campaign.py on v5e, 2026-07-31
# (campaign.jsonl, min-stat slope protocol): (512, 2048) wins the fwd sweep
# at both 4k (879 us, 78 TFLOP/s) and 16k (10.5 ms, 105 TFLOP/s) and the
# fwd+bwd sweep at 4k (2.0 ms, ~119 TFLOP/s); the round-1 defaults
# (256, 512) measure 2.5x slower fwd at 4k. Both kernels clamp tiles to the
# actual shape, so the table is safe for short sequences too.
_TRAIN_TILES = (
    (float("inf"), 512, 2048),
)


def _train_tile(t: int):
    for bound, bq, bk in _TRAIN_TILES:
        if t <= bound:
            return bq, bk
    raise AssertionError("unreachable")


def default_block_size(impl: str, tk: int) -> int:
    return decode_block_k(tk) if impl == "pallas_decode" else _train_tile(tk)[1]


def default_block_q(tq: int, tk: int) -> int:
    """Q-tile length for the Q-tiled Pallas kernel (fwd + bwd)."""
    return _train_tile(tq)[0]
