"""Attention ops: one contract, several implementations.

``flash_attention(q, k, v, ...) -> (out, lse)`` is the framework-wide kernel
contract (the reference's ``flash_res_lse``, ``/root/reference/model.py:60-83``,
done right). Implementations:

- ``"naive"``     — materialised scores, test oracle (:mod:`.reference`)
- ``"blockwise"`` — online-softmax ``lax.scan``, any backend (:mod:`.reference`)
- ``"pallas"``    — Pallas TPU kernels, fwd (:mod:`.pallas_attention`) +
  bwd (:mod:`.pallas_bwd`); Q-tiled, the training shape
- ``"pallas_decode"`` — Pallas TPU split-KV flash-decode kernel
  (:mod:`.pallas_decode`); GQA-group-packed Q tiles for Tq < 128
- ``"auto"``      — decode shapes (Tq < 128) resolve to the flash-decode
  kernel on TPU (any context length; no score transient) and to ``naive``
  elsewhere when the score transient is small; large-Tq shapes resolve to
  ``pallas`` on TPU (``TREE_ATTN_AUTO_PALLAS=0`` opts out of both kernels;
  the decode paths read the variable once at import, so set it before
  importing the package) and ``blockwise`` elsewhere. Pass an explicit impl
  when a specific kernel or backward path must be used.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

from tree_attention_tpu.ops.decode import flash_decode  # noqa: F401
from tree_attention_tpu.ops.reference import (  # noqa: F401
    attention_blockwise,
    attention_naive,
    finalize,
    merge_partials,
)

_IMPLS = ("auto", "naive", "blockwise", "pallas", "pallas_decode")


def _on_tpu(q=None) -> bool:
    """Whether this computation targets TPU.

    A concrete array's placement is authoritative (a CPU-placed array on a
    TPU-default host must not select the Mosaic kernel); tracers carry no
    devices, so jit callers fall back to the default backend — sharded entry
    points resolve from their mesh instead (see ``parallel/tree.py``).
    """
    if q is not None and not isinstance(q, jax.core.Tracer):
        try:
            return {d.platform for d in q.devices()} == {"tpu"}
        except Exception:
            pass
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # no backends initialised
        return False


def mesh_platforms(mesh):
    """The set of device platforms a mesh spans, or None when the mesh has
    no concrete devices to probe (e.g. an AbstractMesh) — callers should
    then trust the compiled path rather than pessimise."""
    try:
        return {d.platform for d in mesh.devices.flat}
    except Exception:
        return None


def resolve_impl_for_mesh(impl: str, mesh) -> str:
    """Pin ``impl='auto'`` for computations running on ``mesh``'s devices.

    Inside ``shard_map``/``jit`` the arrays are tracers, so
    :func:`flash_attention`'s own auto resolution can only consult the
    default backend — wrong when the mesh lives on a different platform
    (e.g. an emulated CPU mesh on a TPU-default host). Sharded entry points
    call this with their mesh before tracing: when the mesh's platform is
    the default backend (or TPU, where every auto branch is valid), "auto"
    passes through; otherwise the portable blockwise path is pinned.
    """
    if impl != "auto":
        return impl
    platforms = mesh_platforms(mesh)
    if platforms is None:
        return impl
    if platforms == {"tpu"}:
        return impl
    try:
        if platforms == {jax.default_backend()}:
            return impl
    except RuntimeError:
        pass
    return "blockwise"


def _pallas_available() -> bool:
    try:
        import tree_attention_tpu.ops.pallas_attention  # noqa: F401
        return True
    except ImportError:
        return False


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    impl: str = "auto",
    block_size: Optional[int] = None,
    block_q: Optional[int] = None,
    custom_vjp: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Compute attention over the sequence axis, returning ``(out, lse)``.

    Args:
      q: ``(B, Hq, Tq, D)`` queries.
      k, v: ``(B, Hkv, Tk, D)`` keys/values; ``Hq % Hkv == 0`` (GQA).
      causal: apply causal masking (``-inf`` before softmax).
      scale: logit scale; default ``D**-0.5``.
      q_offset / kv_offset: global positions of the first local query/key row,
        for causal masking across sequence shards.
      impl: ``auto | naive | blockwise | pallas | pallas_decode``.
      block_size: KV block length for the blockwise/pallas paths. ``None``
        picks the impl's default from :mod:`.tuning` — a measured
        context-bucketed table for the flash-decode kernel, 512 elsewhere;
        an explicit value is honored as given.
      block_q: Q-tile length for the Q-tiled Pallas kernel (fwd and bwd).
        ``None`` picks the tuned default; ignored by the other impls (the
        flash-decode kernel derives its Q packing from the GQA group).
      custom_vjp: use the flash (recompute-from-lse) backward — O(T) residual
        memory but **reverse-mode only** (``jax.jvp``/``jacfwd`` raise on
        custom_vjp functions). Pass False (or ``impl='naive'``) for
        forward-mode differentiability at O(T²) memory.

    Returns:
      ``out``: ``(B, Hq, Tq, D)`` in q's dtype; ``lse``: ``(B, Hq, Tq)``
      float32 logsumexp of the scaled logits (the merge currency of the tree
      reduction).
    """
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {_IMPLS}, got {impl!r}")
    if impl == "auto":
        # Resolution order, all measured on the target chip (TPU v5e):
        # 1. Decode shapes (Tq < 128) on TPU -> "pallas_decode": the
        #    split-KV kernel streams KV at the HBM roofline regardless of
        #    context length (no score transient, GQA streams each KV head
        #    once). This removes round 1's cliff where >=683k-token MHA
        #    decode fell off the naive path's 128 MB transient gate.
        # 2. Decode shapes elsewhere -> "naive" when the score transient is
        #    small (fused two-matmul form; raw autodiff fine for inference).
        #    Gated on 3x the score bytes (f32 logits + masked copy +
        #    probabilities all materialise) staying comfortably small.
        # 3. Large-Tq shapes on TPU -> "pallas" (Q-tiled): verified correct
        #    on-chip and ~4x the blockwise fwd throughput / ~2.3x fwd+bwd
        #    (bf16 operands on the MXU fast path, f32 accumulation).
        #    TREE_ATTN_AUTO_PALLAS=0 opts out of both TPU kernels.
        # 4. Everything else -> "blockwise" (pure XLA, any backend).
        Tq, Tk = q.shape[2], k.shape[2]
        transient_bytes = 3 * q.shape[0] * q.shape[1] * Tq * Tk * 4
        pallas_ok = (
            os.environ.get("TREE_ATTN_AUTO_PALLAS", "1") != "0"
            and _on_tpu(q)
            and _pallas_available()
        )
        # custom_vjp=False is the documented forward-mode-AD escape hatch;
        # raw Pallas forwards have no autodiff rules, so that request keeps
        # the jnp impls whenever one is viable at the shape.
        naive_ok = Tq <= 8 and transient_bytes <= 128 * 1024 * 1024
        if pallas_ok and (custom_vjp or not naive_ok or Tq >= 128):
            from tree_attention_tpu.ops.tuning import tpu_kernel_for

            impl = tpu_kernel_for(Tq)
        elif naive_ok:
            impl = "naive"
        else:
            impl = "blockwise"
    # None picks tuned defaults. The bwd kernels get their own (VMEM-capped)
    # default Q tile only when the caller left block_q to the table; an
    # explicit block_q flows to both passes unchanged so tuning sweeps
    # measure what they label.
    block_q_bwd = block_q
    if block_size is None or (block_q is None and impl == "pallas"):
        from tree_attention_tpu.ops.tuning import (
            default_block_q,
            default_block_q_bwd,
            default_block_size,
        )

        if block_size is None:
            block_size = default_block_size(impl, k.shape[2])
        if block_q is None and impl == "pallas":
            block_q = default_block_q(q.shape[2], k.shape[2])
            # The resolved KV tile (possibly caller-supplied) bounds the
            # bwd Q tile: VMEM feasibility scales with bq * bk.
            block_q_bwd = default_block_q_bwd(
                q.shape[2], k.shape[2], block_size
            )
    if impl == "naive":
        # Raw autodiff path: the differential oracle the custom VJP is
        # tested against.
        return attention_naive(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset, kv_offset=kv_offset
        )
    if impl in ("pallas", "pallas_decode"):
        try:
            import tree_attention_tpu.ops.pallas_attention  # noqa: F401
        except ImportError as e:
            raise NotImplementedError(
                f"impl={impl!r} requested but the Pallas kernel module is not "
                "available in this build; use impl='blockwise' or 'auto'"
            ) from e
    if not custom_vjp:
        if impl == "blockwise":
            return attention_blockwise(
                q, k, v, causal=causal, scale=scale, q_offset=q_offset,
                kv_offset=kv_offset, block_size=block_size,
            )
        # Raw Pallas forwards: fine for inference; they have no autodiff
        # rules at all, so this is never silently worse than the custom VJP.
        if impl == "pallas_decode":
            from tree_attention_tpu.ops.pallas_decode import (
                attention_pallas_decode,
            )

            return attention_pallas_decode(
                q, k, v, causal=causal, scale=scale, q_offset=q_offset,
                kv_offset=kv_offset, block_size=block_size,
            )
        from tree_attention_tpu.ops.pallas_attention import attention_pallas_fwd

        kw = {} if block_q is None else {"block_q": block_q}
        return attention_pallas_fwd(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            kv_offset=kv_offset, block_size=block_size, **kw,
        )
    from tree_attention_tpu.ops.vjp import flash_attention_vjp

    return flash_attention_vjp(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset,
        kv_offset=kv_offset, impl=impl, block_size=block_size,
        block_q=block_q if impl == "pallas" else None,
        block_q_bwd=block_q_bwd if impl == "pallas" else None,
    )
