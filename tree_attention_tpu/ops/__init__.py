"""Attention ops: one contract, several implementations.

``flash_attention(q, k, v, ...) -> (out, lse)`` is the framework-wide kernel
contract (the reference's ``flash_res_lse``, ``/root/reference/model.py:60-83``,
done right). Implementations:

- ``"naive"``     — materialised scores, test oracle (:mod:`.reference`)
- ``"blockwise"`` — online-softmax ``lax.scan``, any backend (:mod:`.reference`)
- ``"pallas"``    — Pallas TPU kernels, fwd (:mod:`.pallas_attention`) +
  bwd (:mod:`.pallas_bwd`)
- ``"auto"``      — small-Tq MHA decode shapes resolve to ``naive`` (the
  fused two-matmul form runs nearest the HBM roofline there, and its raw
  autodiff is fine for inference); everything else is blockwise, resolving
  to pallas on TPU only when ``TREE_ATTN_AUTO_PALLAS=1`` (opt-in until the
  kernel is verified on the target chip). Pass an explicit impl when the
  O(T)-residual custom-VJP backward or a specific kernel must be used.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax

from tree_attention_tpu.ops.decode import flash_decode  # noqa: F401
from tree_attention_tpu.ops.reference import (  # noqa: F401
    attention_blockwise,
    attention_naive,
    finalize,
    merge_partials,
)

_IMPLS = ("auto", "naive", "blockwise", "pallas")


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # no backends initialised
        return False


def _pallas_available() -> bool:
    try:
        import tree_attention_tpu.ops.pallas_attention  # noqa: F401
        return True
    except ImportError:
        return False


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    impl: str = "auto",
    block_size: int = 512,
    custom_vjp: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Compute attention over the sequence axis, returning ``(out, lse)``.

    Args:
      q: ``(B, Hq, Tq, D)`` queries.
      k, v: ``(B, Hkv, Tk, D)`` keys/values; ``Hq % Hkv == 0`` (GQA).
      causal: apply causal masking (``-inf`` before softmax).
      scale: logit scale; default ``D**-0.5``.
      q_offset / kv_offset: global positions of the first local query/key row,
        for causal masking across sequence shards.
      impl: ``auto | naive | blockwise | pallas``.
      block_size: KV block length for the blockwise/pallas paths.
      custom_vjp: use the flash (recompute-from-lse) backward — O(T) residual
        memory but **reverse-mode only** (``jax.jvp``/``jacfwd`` raise on
        custom_vjp functions). Pass False (or ``impl='naive'``) for
        forward-mode differentiability at O(T²) memory.

    Returns:
      ``out``: ``(B, Hq, Tq, D)`` in q's dtype; ``lse``: ``(B, Hq, Tq)``
      float32 logsumexp of the scaled logits (the merge currency of the tree
      reduction).
    """
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {_IMPLS}, got {impl!r}")
    if impl == "auto":
        # Pallas-on-TPU stays opt-in until verified on the target chip (the
        # current axon tunnel wedges in Mosaic compile — see
        # .claude/skills/verify/SKILL.md); the XLA blockwise path is the safe
        # default everywhere — except MHA decode shapes, where the
        # materialised path wins: at tiny Tq the score matrix is a few MB,
        # and fusing two large matmuls without a scan runs at ~95% of HBM
        # roofline on v5e vs ~81% for the blockwise scan (measured, 64k ctx).
        # Gated on Hq == Hkv because attention_naive expands GQA KV to Hq
        # heads (group-factor HBM blowup the blockwise path avoids), and on
        # 3x the score bytes (f32 logits + masked copy + probabilities are
        # each materialised) staying comfortably small.
        Tq, Tk = q.shape[2], k.shape[2]
        transient_bytes = 3 * q.shape[0] * q.shape[1] * Tq * Tk * 4
        if (
            Tq <= 8
            and q.shape[1] == k.shape[1]
            and transient_bytes <= 128 * 1024 * 1024
        ):
            impl = "naive"
        elif (
            os.environ.get("TREE_ATTN_AUTO_PALLAS") == "1"
            and _on_tpu()
            and _pallas_available()
        ):
            impl = "pallas"
        else:
            impl = "blockwise"
    if impl == "naive":
        # Raw autodiff path: the differential oracle the custom VJP is
        # tested against.
        return attention_naive(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset, kv_offset=kv_offset
        )
    if impl == "pallas":
        try:
            import tree_attention_tpu.ops.pallas_attention  # noqa: F401
        except ImportError as e:
            raise NotImplementedError(
                "impl='pallas' requested but the Pallas kernel module is not "
                "available in this build; use impl='blockwise' or 'auto'"
            ) from e
    if not custom_vjp:
        if impl == "blockwise":
            return attention_blockwise(
                q, k, v, causal=causal, scale=scale, q_offset=q_offset,
                kv_offset=kv_offset, block_size=block_size,
            )
        # Raw Pallas forward: fine for inference; has no autodiff rules at
        # all, so this is never silently worse than the custom VJP.
        from tree_attention_tpu.ops.pallas_attention import attention_pallas_fwd

        return attention_pallas_fwd(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            kv_offset=kv_offset, block_size=block_size,
        )
    from tree_attention_tpu.ops.vjp import flash_attention_vjp

    return flash_attention_vjp(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset,
        kv_offset=kv_offset, impl=impl, block_size=block_size,
    )
