"""Pallas TPU split-KV flash-decode kernel for small-Tq (inference) shapes.

Decode is the reference's entire workload (``/root/reference/model.py:140-145``:
one query token against a 64k-token KV buffer). It is bandwidth-bound — the
chip must stream every KV byte once — so the kernel's only job is to keep the
per-KV-row compute cost below the HBM delivery rate.

Layout (chosen by measurement on v5e; see the design notes below):

- **Q-major scores.** The score tile is ``(r8, block_k)``: packed query rows
  on sublanes (padded to a multiple of 8), KV positions across lanes. The
  QKᵀ matmul is then ``(r8, D) x (D, block_k)`` — ``r8·D·block_k`` MACs, a
  factor ``128/r8`` cheaper than a KV-major layout that pads queries to the
  128-lane width. A KV-major variant measured MXU-bound at ~25% of the HBM
  roofline for MHA decode precisely because of that padding; this layout's
  matmul cost is ~``block_k/16`` MXU cycles per tile against a DMA cost of
  ~``0.6·block_k`` cycles — comfortably DMA-bound.
- **The GQA group rides in the Q tile.** Queries are packed per KV head as
  ``(group × Tq)`` rows, and the grid runs over ``B·Hkv``, so each KV head's
  stream is read exactly **once** regardless of group size. (The Q-tiled
  training kernel instead re-reads KV per query head: measured 12% of
  roofline on GQA-8 decode, 8× the necessary bytes.)
- **Split-KV as the sequential grid dimension.** KV tiles iterate in the
  last grid dimension with the running online-softmax state ``(m, l, acc)``
  in VMEM scratch — the in-kernel mirror of
  :func:`tree_attention_tpu.ops.reference.merge_partials`, so the emitted
  ``(out, lse)`` plugs into the cross-device tree merge unchanged.
- Causal masking uses global offsets from SMEM (they are traced values
  inside jitted decode steps); tiles whose every KV position is masked skip
  both matmuls via ``pl.when``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tree_attention_tpu import obs
from tree_attention_tpu.ops.block_utils import (
    LANES as _LANES,
    NEG_INF,
    matmul_precision,
    offsets_smem as _offsets_smem,
    pad_to_block as _pad_dim,
    tpu_compiler_params,
)

# The wrappers below are jitted, so their Python bodies run once per
# distinct (shape, config): this counts kernel program BUILDS — a
# recompile storm (e.g. a caller advancing a static q_position per token)
# shows up here as a runaway count. Execution totals live in the host
# loops (bench/harness.py, cli.py).
_KERNEL_BUILDS = obs.counter(
    "pallas_decode_kernel_builds_total",
    "flash-decode kernel program builds (one per distinct shape/config)",
    labels=("kernel",),
)


def _decode_visibility_mask(s, qi, si, *, bq, bk, tq, tk,
                            q_offset, kv_offset, causal, tree_bits=None):
    """Ragged-tail + causal masking for one (bq, bk) decode score tile —
    the ONE mask definition shared by the bf16-cast and int8-MXU kernels.

    Lane i is KV global position kv_offset + si*bk + i; sublane j is query
    row ((qi*bq + j) % Tq) at global position q_offset + that. Padded rows
    (j >= r) alias a real query's position and compute a duplicate row the
    host slices away. Broadcast form: (bq, 1) row positions vs (1, bk)
    column positions — one broadcast compare, no full-tile iota
    materialisation (see block_utils.mask_scores for why not a lax.cond
    interior skip). Static no-op for non-causal divisible shapes.

    ``tree_bits`` (a ``(bq, 1)`` int32 tile of per-PACKED-row ancestor
    bitmasks; requires ``causal`` and ``tq <= 32``) replaces the plain
    causal rule with the speculative tree-verification window rule
    (SpecInfer, arXiv:2305.09781): the tq query rows occupy KV positions
    ``[q_offset, q_offset + tq)`` of their slot, and row ``j`` sees window
    position ``i`` iff bit ``i`` of its mask is set; positions below the
    window stay visible (committed history), positions past it never are.
    A lower-triangular bitmask reproduces causal masking bit-for-bit.
    """
    needs_ragged = tk % bk != 0
    if tree_bits is not None:
        col_idx = si * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        rel = kv_offset + col_idx - q_offset  # window-relative KV position
        # Per-element logical shift; rel >= tq columns fail the window
        # check regardless, so the clip only keeps the shift in-range.
        bit = jax.lax.shift_right_logical(
            jnp.broadcast_to(tree_bits, (bq, bk)),
            jnp.broadcast_to(jnp.clip(rel, 0, 31), (bq, bk)),
        ) & 1
        valid = (rel < 0) | ((rel < tq) & (bit == 1))
        if needs_ragged:
            valid &= col_idx < tk
        return jnp.where(valid, s, NEG_INF)
    if not (causal or needs_ragged):
        return s
    col_idx = si * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    valid = None
    if needs_ragged:
        valid = col_idx < tk
    if causal:
        q_pos = q_offset + (
            (qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)) % tq
        )
        c = (kv_offset + col_idx) <= q_pos
        valid = c if valid is None else valid & c
    return jnp.where(valid, s, NEG_INF)


def _decode_softmax_fold(s, v_tile, m_scr, l_scr, acc_scr, *, si, bk, tk,
                         v_scale=None):
    """Fold one masked score tile and its V tile into the running
    online-softmax state — shared by both decode kernels.

    P·V with the FA2 p-downcast (probabilities are in [0,1], bf16 relative
    error stays small), f32 accumulation. When Tk is ragged the last tile's
    trailing V rows are unspecified garbage (Pallas loads the partial block
    unpadded; interpret mode NaN-poisons it) — p's masked columns are
    exactly 0, but 0·NaN = NaN, so those rows must be zeroed. Static no-op
    for divisible shapes.

    ``v_scale`` (a scalar — the per-BLOCK V dequantization scale of a
    paged int8 tile, ISSUE 13) multiplies ``p`` AFTER the running sum
    ``l`` is taken: the softmax normalizer is over the (dequantized)
    scores only, the scale belongs to the V values — ``p·(v_q·s) ==
    (p·s)·v_q``, one scalar multiply on the probability tile instead of
    a per-element dequant of the V stream.
    """
    m_prev = m_scr[:, :1]  # (bq, 1)
    l_prev = l_scr[:, :1]
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
    alpha = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_safe))
    p = jnp.exp(s - m_safe)  # (bq, bk); masked cols are exactly 0
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if v_scale is not None:
        p = p * v_scale
    if v_tile.dtype == jnp.int8:
        v_tile = v_tile.astype(jnp.bfloat16)
    if tk % bk:
        row_ok = (
            si * bk + lax.broadcasted_iota(jnp.int32, v_tile.shape, 0)
        ) < tk
        v_tile = jnp.where(row_ok, v_tile, 0)
    acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
        p.astype(v_tile.dtype), v_tile,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=matmul_precision(v_tile.dtype, v_tile.dtype),
    )
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _decode_finalize(out_ref, lse_ref, m_scr, l_scr, acc_scr):
    """Emit (out, lse) from the final online-softmax state — shared by both
    decode kernels. Rows with no visible keys emit 0 / -inf."""
    m = m_scr[:, :1]
    l = l_scr[:, :1]
    empty = l <= 0.0
    l_safe = jnp.where(empty, 1.0, l)
    out_ref[0] = (
        jnp.where(empty, 0.0, acc_scr[...] / l_safe)
    ).astype(out_ref.dtype)
    lse = jnp.where(
        empty, NEG_INF, jnp.where(m == NEG_INF, 0.0, m) + jnp.log(l_safe)
    )
    lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_decode_kernel(
    offs_ref,  # SMEM (2, B): per-batch [q_offset | kv_offset] columns —
               # ragged caches give every batch row its own global position
    *refs,     # q_ref, [tb_ref when tree], k_ref, v_ref, out_ref, lse_ref,
               # m_scr, l_scr, acc_scr:
               #   tb_ref  VMEM (1, bq, LANES) int32 — per-packed-row tree
               #           ancestor bitmasks (lane-broadcast), tree=True only
               #   q_ref   VMEM (1, bq, D) — packed (group × Tq) queries of
               #           one KV head
               #   k/v_ref VMEM (1, bk, D)
               #   out_ref VMEM (1, bq, D)
               #   lse_ref VMEM (1, bq, LANES) — lse broadcast across lanes
               #           (host slices lane 0; TPU tiling wants a
               #           128-multiple trailing dim)
               #   m/l_scr VMEM (bq, LANES) f32 — running max / sum
               #   acc_scr VMEM (bq, D) f32
    scale: float,
    causal: bool,
    tk: int,
    tq: int,
    block_q: int,
    block_k: int,
    n_kv_heads: int,
    tree: bool = False,
):
    if tree:
        q_ref, tb_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        tb_ref = None
    qi = pl.program_id(1)
    si = pl.program_id(2)
    n_s = pl.num_programs(2)

    b = pl.program_id(0) // n_kv_heads  # grid dim 0 runs over B·Hkv
    q_offset = offs_ref[0, b]
    kv_offset = offs_ref[1, b]

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bq, bk = block_q, block_k

    # Tile liveness: skip both matmuls when every KV position of this tile is
    # invisible — beyond Tk (host padding), or, under causality, past the
    # most visible query row of this Q tile. Packed row j is query index
    # (j % Tq), so the tile's maximum query position is q_offset + Tq - 1.
    live = si * bk < tk
    if causal:
        live &= (kv_offset + si * bk) <= (q_offset + tq - 1)

    @pl.when(live)
    def _compute():
        # Scores (bq, bk): packed queries on sublanes, KV across lanes.
        # Operands stay in their native dtype (bf16 MXU fast path) with f32
        # accumulation; see matmul_precision for the precision contract.
        # int8 K/V (the quantized-cache path) casts to bf16 first — exact
        # for values in [-127, 127], and dot_general rejects mixed dtypes.
        k_tile = k_ref[0]
        if k_tile.dtype == jnp.int8:
            k_tile = k_tile.astype(jnp.bfloat16)
        s = lax.dot_general(
            q_ref[0],
            k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(q_ref.dtype, k_tile.dtype),
        ) * scale  # (bq, bk) f32

        s = _decode_visibility_mask(
            s, qi, si, bq=bq, bk=bk, tq=tq, tk=tk,
            q_offset=q_offset, kv_offset=kv_offset, causal=causal,
            tree_bits=None if tb_ref is None else tb_ref[0][:, :1],
        )
        _decode_softmax_fold(
            s, v_ref[0], m_scr, l_scr, acc_scr, si=si, bk=bk, tk=tk
        )

    @pl.when(si == n_s - 1)
    def _finalize():
        _decode_finalize(out_ref, lse_ref, m_scr, l_scr, acc_scr)


def _flash_decode_q8q_kernel(
    offs_ref,  # SMEM (2, B): per-batch [q_offset | kv_offset] columns
    *refs,     # q_ref, qs_ref, [tb_ref when tree], k_ref, v_ref, out_ref,
               # lse_ref, m_scr, l_scr, acc_scr:
               #   tb_ref  VMEM (1, bq, LANES) int32 — tree bitmasks
               #   q_ref   VMEM (1, bq, D) int8 — per-row-quantized,
               #           scale-folded Q
               #   qs_ref  VMEM (1, bq, LANES) f32 — per-row Q scales
               #   k/v_ref VMEM (1, bk, D) int8
               #   out_ref VMEM (1, bq, D); lse_ref VMEM (1, bq, LANES)
               #   m/l_scr VMEM (bq, LANES) f32; acc_scr VMEM (bq, D) f32
    causal: bool,
    tk: int,
    tq: int,
    block_q: int,
    block_k: int,
    n_kv_heads: int,
    tree: bool = False,
):
    """The int8-MXU variant of :func:`_flash_decode_kernel`: scores run
    natively int8 x int8 -> int32 (no K dequant cast on the KV stream — the
    bf16-cast kernel's dominant per-tile VPU cost) and are rescaled by the
    per-row Q scale, one (bq, 1)-broadcast multiply. Measured 92.0% of the
    int8 roofline at 64k ctx vs 85.7% for the cast kernel
    (measurements/r3/experiment_q8q.jsonl). Same online-softmax state and
    ``(out, lse)`` contract; the lse is of the dequantized logits, so the
    output plugs into the tree merge unchanged."""
    if tree:
        q_ref, qs_ref, tb_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
    else:
        q_ref, qs_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
        tb_ref = None
    qi = pl.program_id(1)
    si = pl.program_id(2)
    n_s = pl.num_programs(2)

    b = pl.program_id(0) // n_kv_heads
    q_offset = offs_ref[0, b]
    kv_offset = offs_ref[1, b]

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bq, bk = block_q, block_k

    live = si * bk < tk
    if causal:
        live &= (kv_offset + si * bk) <= (q_offset + tq - 1)

    @pl.when(live)
    def _compute():
        s_i = lax.dot_general(
            q_ref[0],
            k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        s = s_i.astype(jnp.float32) * qs_ref[0][:, :1]  # (bq, bk) f32

        s = _decode_visibility_mask(
            s, qi, si, bq=bq, bk=bk, tq=tq, tk=tk,
            q_offset=q_offset, kv_offset=kv_offset, causal=causal,
            tree_bits=None if tb_ref is None else tb_ref[0][:, :1],
        )
        _decode_softmax_fold(
            s, v_ref[0], m_scr, l_scr, acc_scr, si=si, bk=bk, tk=tk
        )

    @pl.when(si == n_s - 1)
    def _finalize():
        _decode_finalize(out_ref, lse_ref, m_scr, l_scr, acc_scr)


def _flash_decode_paged_kernel(
    offs_ref,  # SMEM (2, B) scalar-prefetch: per-batch [q_offset|kv_offset]
    tbl_ref,   # SMEM (B, NB) scalar-prefetch block table — read by the
               # K/V index maps, not the body: grid step si streams pool
               # block table[b, si] (PagedAttention, arXiv:2309.06180)
    *refs,     # q_ref, [tb_ref when tree], k_ref, v_ref, out_ref, lse_ref,
               # m_scr, l_scr, acc_scr:
               #   q_ref   VMEM (1, bq, D) — packed (group × Tq) queries
               #   tb_ref  VMEM (1, bq, LANES) int32 — tree bitmasks
               #   k/v_ref VMEM (1, 1, block, D) — pool block tbl[b, si]
               #   out_ref VMEM (1, bq, D); lse_ref VMEM (1, bq, LANES)
               #   m/l_scr VMEM (bq, LANES) f32; acc_scr VMEM (bq, D) f32
    scale: float,
    causal: bool,
    tq: int,
    block_q: int,
    block_k: int,
    n_kv_heads: int,
    tree: bool = False,
    block_scales: bool = False,
    local_blocks: bool = False,
):
    """Block-table variant of :func:`_flash_decode_kernel`: the split-KV
    grid dimension walks each slot's LOGICAL blocks and the BlockSpec
    index maps dereference the scalar-prefetched table, so fragmented /
    non-monotone physical layouts stream exactly like a contiguous
    buffer. The logical capacity ``NB·block`` is block-divisible by
    construction, so the ragged-tail mask is statically off; the causal
    mask against each slot's own ``q_offset`` hides every unwritten (or
    garbage-mapped) position, and the per-slot liveness cull skips whole
    blocks past the slot's length — a short slot reads only its own few
    blocks of the pool.

    ``block_scales`` (ISSUE 13, the shareable-int8 pool): two extra
    lane-broadcast operands carry each logical block's K and V
    dequantization SCALARS — K's multiplies the score tile after the
    matmul (a scalar commutes out of the dot product, so no per-element
    K dequant rides the KV stream), V's folds into ``p`` (see
    :func:`_decode_softmax_fold`).

    ``local_blocks`` (ISSUE 18, the sequence-sharded pool): the table is
    SIGNED — a negative entry marks a logical block another shard owns.
    The index map clamps the DMA to pool row 0 (some valid row must
    stream), and the body's liveness gate skips folding it, so the
    online-softmax state accumulates exactly this shard's partial; rows
    whose every block is remote finalize to the ``(0, -inf)`` merge
    identity that :func:`tree_attention_tpu.parallel.tree._weigh`
    absorbs."""
    if not local_blocks:
        del tbl_ref  # consumed by the index maps
    ks_ref = vs_ref = None
    if tree and block_scales:
        q_ref, tb_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
    elif tree:
        q_ref, tb_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
    elif block_scales:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
        tb_ref = None
    else:
        q_ref, k_ref, v_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        tb_ref = None
    qi = pl.program_id(1)
    si = pl.program_id(2)
    n_s = pl.num_programs(2)
    tk = n_s * block_k  # logical capacity; block-divisible by construction

    b = pl.program_id(0) // n_kv_heads
    q_offset = offs_ref[0, b]
    kv_offset = offs_ref[1, b]

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bq, bk = block_q, block_k

    live = si * bk < tk
    if causal:
        live &= (kv_offset + si * bk) <= (q_offset + tq - 1)
    if local_blocks:
        live &= tbl_ref[b, si] >= 0

    @pl.when(live)
    def _compute():
        k_tile = k_ref[0, 0]
        if k_tile.dtype == jnp.int8:
            k_tile = k_tile.astype(jnp.bfloat16)
        s = lax.dot_general(
            q_ref[0],
            k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=matmul_precision(q_ref.dtype, k_tile.dtype),
        ) * scale
        if ks_ref is not None:
            s = s * ks_ref[0, 0, 0]  # this block's K dequant scalar

        s = _decode_visibility_mask(
            s, qi, si, bq=bq, bk=bk, tq=tq, tk=tk,
            q_offset=q_offset, kv_offset=kv_offset, causal=causal,
            tree_bits=None if tb_ref is None else tb_ref[0][:, :1],
        )
        _decode_softmax_fold(
            s, v_ref[0, 0], m_scr, l_scr, acc_scr, si=si, bk=bk, tk=tk,
            v_scale=None if vs_ref is None else vs_ref[0, 0, 0],
        )

    @pl.when(si == n_s - 1)
    def _finalize():
        _decode_finalize(out_ref, lse_ref, m_scr, l_scr, acc_scr)


def _flash_decode_paged_q8q_kernel(
    offs_ref,  # SMEM (2, B) scalar-prefetch
    tbl_ref,   # SMEM (B, NB) scalar-prefetch block table
    *refs,     # q_ref, qs_ref, [tb_ref when tree], k_ref, v_ref, out_ref,
               # lse_ref, m_scr, l_scr, acc_scr:
               #   q_ref   VMEM (1, bq, D) int8 — per-row-quantized,
               #           scale-folded Q
               #   qs_ref  VMEM (1, bq, LANES) f32 — per-row Q scales
               #   tb_ref  VMEM (1, bq, LANES) int32 — tree bitmasks
               #   k/v_ref VMEM (1, 1, block, D) int8 — pool block
               #           tbl[b, si]
    causal: bool,
    tq: int,
    block_q: int,
    block_k: int,
    n_kv_heads: int,
    tree: bool = False,
    block_scales: bool = False,
):
    """Block-table variant of :func:`_flash_decode_q8q_kernel` — same
    int8-MXU score path, KV streamed through the scalar-prefetched
    table (see :func:`_flash_decode_paged_kernel`). With
    ``block_scales`` (ISSUE 13) the per-BLOCK K/V dequant scalars ride
    two extra lane-broadcast operands: K's joins the per-row Q scale in
    the post-matmul rescale (both are scalars w.r.t. the int8 dot, so
    the MXU path stays int8 × int8 → int32), V's folds into ``p``."""
    del tbl_ref
    ks_ref = vs_ref = None
    if tree and block_scales:
        q_ref, qs_ref, tb_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, \
            lse_ref, m_scr, l_scr, acc_scr = refs
    elif tree:
        q_ref, qs_ref, tb_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
    elif block_scales:
        q_ref, qs_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
        tb_ref = None
    else:
        q_ref, qs_ref, k_ref, v_ref, out_ref, lse_ref, \
            m_scr, l_scr, acc_scr = refs
        tb_ref = None
    qi = pl.program_id(1)
    si = pl.program_id(2)
    n_s = pl.num_programs(2)
    tk = n_s * block_k

    b = pl.program_id(0) // n_kv_heads
    q_offset = offs_ref[0, b]
    kv_offset = offs_ref[1, b]

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    bq, bk = block_q, block_k

    live = si * bk < tk
    if causal:
        live &= (kv_offset + si * bk) <= (q_offset + tq - 1)

    @pl.when(live)
    def _compute():
        s_i = lax.dot_general(
            q_ref[0],
            k_ref[0, 0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        s = s_i.astype(jnp.float32) * qs_ref[0][:, :1]
        if ks_ref is not None:
            s = s * ks_ref[0, 0, 0]  # this block's K dequant scalar

        s = _decode_visibility_mask(
            s, qi, si, bq=bq, bk=bk, tq=tq, tk=tk,
            q_offset=q_offset, kv_offset=kv_offset, causal=causal,
            tree_bits=None if tb_ref is None else tb_ref[0][:, :1],
        )
        _decode_softmax_fold(
            s, v_ref[0, 0], m_scr, l_scr, acc_scr, si=si, bk=bk, tk=tk,
            v_scale=None if vs_ref is None else vs_ref[0, 0, 0],
        )

    @pl.when(si == n_s - 1)
    def _finalize():
        _decode_finalize(out_ref, lse_ref, m_scr, l_scr, acc_scr)


def _paged_q_map(bh, qi, si, offs_ref, tbl_ref):
    """Q/out/lse index map of the paged decode grid (table unused)."""
    del si, offs_ref, tbl_ref
    return (bh, qi, 0)


def _paged_kv_map(n_kv_heads: int, local: bool = False):
    """K/V index map: grid step ``si`` loads pool block
    ``table[b, si]`` of head ``bh % Hkv`` — the block-table indirection
    happens HERE, in the prefetch-driven DMA schedule, not in the body.

    ``local`` (ISSUE 18): the table is signed; a negative entry marks a
    block this shard does not own. The DMA engine still needs SOME valid
    pool row, so the map clamps to 0 — the body's ``tbl_ref[b, si] >= 0``
    gate drops the streamed tile before it touches the softmax state."""

    def index_map(bh, qi, si, offs_ref, tbl_ref):
        del qi, offs_ref
        t = tbl_ref[bh // n_kv_heads, si]
        if local:
            t = jnp.maximum(t, 0)
        return (t, bh % n_kv_heads, 0, 0)

    return index_map


def _paged_scale_map(bh, qi, si, offs_ref, tbl_ref):
    """Per-block scale operand map (ISSUE 13): the scales were pre-
    gathered per LOGICAL block (see :func:`_block_scale_rows`), so grid
    step ``si`` just reads row ``si`` — no second table dereference."""
    del qi, offs_ref, tbl_ref
    return (bh, si, 0)


def _block_scale_rows(scale: jax.Array, block_table: jax.Array) -> jax.Array:
    """Arrange ``(N, Hkv)`` per-block scale scalars into the
    ``(B·Hkv, NB, LANES)`` lane-broadcast operand the paged kernels read
    — one scalar per (slot, head, logical block), gathered through the
    table once per call (O(B·NB·Hkv) floats, noise next to the KV bytes
    the grid streams). The same VMEM idiom as the q8q per-row Q scales
    and the tree bitmasks."""
    N, Hkv = scale.shape
    B, NB = block_table.shape
    g = scale[jnp.clip(block_table, 0, N - 1)]      # (B, NB, Hkv)
    g = jnp.moveaxis(g, 2, 1).reshape(B * Hkv, NB)
    return jnp.broadcast_to(g[:, :, None], (B * Hkv, NB, _LANES))


def _paged_decode_call(
    kernel_body,
    kernel_kwargs,
    tensors,
    in_specs,
    *,
    q_offset,
    kv_offset,
    block_table: jax.Array,
    batch: int,
    n_q: int,
    bq: int,
    d: int,
    out_dtype,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Shared ``pallas_call`` plumbing of the paged decode kernels.

    Per-batch offsets AND the ``(B, NB)`` block table ride scalar
    prefetch (``PrefetchScalarGridSpec``), the grid's sequential split-KV
    dimension is the table width — one step per logical block — and the
    K/V index maps dereference the table, so the DMA pipeline prefetches
    physical blocks in logical order with no gather copy."""
    NB = block_table.shape[1]
    BH = tensors[0].shape[0]  # B * Hkv
    offs = _offsets_smem(q_offset, kv_offset, batch)
    tbl = jnp.asarray(block_table, jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, n_q, NB),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), _paged_q_map),
            pl.BlockSpec((1, bq, _LANES), _paged_q_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel_body, **kernel_kwargs),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, n_q * bq, d), out_dtype),
            jax.ShapeDtypeStruct((BH, n_q * bq, _LANES), jnp.float32),
        ],
        # Only the split-KV (table) dim is sequential, as in the
        # contiguous kernels.
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offs, tbl, *tensors)


def _tree_bits_rows(
    tree_mask: jax.Array, G: int, Hkv: int, bq: int, n_q: int
) -> jax.Array:
    """Pack a ``(B, Tq, Tq)`` bool ancestor mask into the per-packed-row
    bitmask operand the decode kernels read: ``(B*Hkv, n_q*bq, LANES)``
    int32, bit ``j`` of packed row ``r`` = query row ``r % Tq`` sees window
    position ``j``. Rows ride VMEM lane-broadcast exactly like the q8q
    per-row Q scales (the kernel reads ``[:, :1]``). Padded rows get 0 —
    their window is fully masked (committed history stays visible) and the
    host slices them away."""
    B, Tq, _ = tree_mask.shape
    # One bit per window column; bit 31 wraps to INT32_MIN, which is the
    # correct bit PATTERN (the kernel shifts logically), and bits never
    # collide, so the sum is a bitwise OR.
    bits = jnp.sum(
        tree_mask.astype(jnp.int32)
        * jnp.left_shift(1, jnp.arange(Tq, dtype=jnp.int32))[None, None, :],
        axis=2,
    )  # (B, Tq)
    rows = jnp.broadcast_to(bits[:, None, None, :], (B, Hkv, G, Tq))
    rows = _pad_dim(rows.reshape(B, Hkv, G * Tq), 2, bq)
    rows = rows.reshape(B * Hkv, n_q * bq, 1)
    return jnp.broadcast_to(rows, (B * Hkv, n_q * bq, _LANES))


def resolve_q8_kernel(kernel: str):
    """The one home of the q8-kernel-name contract: ``"q8q"`` → the int8-MXU
    kernel (:func:`attention_pallas_decode_q8q`), ``"q8"`` → the bf16-cast
    kernel (:func:`attention_pallas_decode_q8`); anything else raises."""
    if kernel == "q8q":
        return attention_pallas_decode_q8q
    if kernel == "q8":
        return attention_pallas_decode_q8
    raise ValueError(f"q8 kernel must be 'q8q' or 'q8', got {kernel!r}")


def quantize_kv_channelwise(
    k: jax.Array, v: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-channel symmetric int8 quantization of a KV buffer.

    Returns ``(k_q, v_q, k_scale, v_scale)``: int8 tensors shaped like k/v
    and float32 scales of shape ``(B, Hkv, 1, D)`` with
    ``k ≈ k_q * k_scale``. Per-channel (one scale per head-dim lane per KV
    head) rather than per-token so the decode kernel never touches the
    scales on the hot KV stream: K's scale folds into Q before the kernel
    and V's applies to the accumulator in the epilogue — both O(D) per
    step, not O(T·D).
    """
    k_q, k_s = quantize_symmetric_int8(k, axis=2)
    v_q, v_s = quantize_symmetric_int8(v, axis=2)
    return k_q, v_q, k_s, v_s


@functools.partial(jax.jit, static_argnames=("axis",))
def quantize_symmetric_int8(x: jax.Array, axis: int):
    """The one definition of the q8 numeric contract the kernels dequant
    against: absmax/127 scale (zero-channel scale = 1.0), f32 intermediate,
    round, clip to ±127, int8. ``axis`` is the reduction (token) axis —
    2 for a (B, Hkv, T, D) buffer, 3 for a (L, B, Hkv, T, D) cache."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_size", "interpret"),
)
def attention_pallas_decode_q8(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
    block_table: Optional[jax.Array] = None,
    tree_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Split-KV flash decode over an int8-quantized KV buffer.

    Same ``(out, lse)`` contract as :func:`attention_pallas_decode`, computed
    over the *dequantized* values ``k_q·k_scale`` / ``v_q·v_scale`` — the lse
    is of the dequantized logits, so the output plugs into the tree merge
    unchanged. Decode is bandwidth-bound (the kernel's whole job is to
    stream every KV byte once), so int8 halves the bytes and doubles the
    tokens/sec ceiling at the same roofline; the scales never ride the KV
    stream (see :func:`quantize_kv_channelwise`).

    Opt-in: quantization is approximate (≈2–3 decimal digits per channel).
    The framework's default path stays exact.
    """
    B, Hq, Tq, D = q.shape
    Hkv = k_q.shape[1]
    if k_q.dtype != jnp.int8 or v_q.dtype != jnp.int8:
        raise ValueError(
            f"k_q/v_q must be int8, got {k_q.dtype}/{v_q.dtype}"
        )
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    if block_table is not None and getattr(k_scale, "ndim", 4) == 2:
        # Per-BLOCK scale scalars (ISSUE 13): the fold-into-Q trick below
        # cannot express a scale that varies along the KV stream, so this
        # shape takes its own paged call — Q rides bf16 un-folded (softmax
        # scale applied by the kernel), each block's K scalar rescales the
        # score tile post-matmul, V's folds into p.
        N = k_q.shape[0]
        if k_scale.shape != (N, Hkv) or v_scale.shape != (N, Hkv):
            raise ValueError(
                f"per-block scales must be (N, Hkv) = {(N, Hkv)}, got "
                f"{k_scale.shape}/{v_scale.shape}"
            )
        if tree_mask is not None:
            if not causal:
                raise ValueError("tree_mask requires causal=True")
            if Tq > 32:
                raise ValueError(
                    f"tree_mask packs into int32 bitmasks: Tq={Tq} "
                    f"exceeds 32"
                )
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        out_dtype = q.dtype
        sm = (D ** -0.5) if scale is None else scale
        r = G * Tq
        bq = min(-(-r // 8) * 8, 128)
        qp = _pad_dim(
            q.astype(jnp.bfloat16).reshape(B, Hkv, r, D), 2, bq
        ).reshape(B * Hkv, -1, D)
        n_q = qp.shape[1] // bq
        blk = k_q.shape[2]
        if obs.REGISTRY.enabled:
            _KERNEL_BUILDS.labels(kernel="paged_q8_block").inc()
        tensors = [qp, k_q, v_q,
                   _block_scale_rows(k_scale, block_table),
                   _block_scale_rows(v_scale, block_table)]
        in_specs = [
            pl.BlockSpec((1, bq, D), _paged_q_map),
            pl.BlockSpec((1, 1, blk, D), _paged_kv_map(Hkv)),
            pl.BlockSpec((1, 1, blk, D), _paged_kv_map(Hkv)),
            pl.BlockSpec((1, 1, _LANES), _paged_scale_map),
            pl.BlockSpec((1, 1, _LANES), _paged_scale_map),
        ]
        if tree_mask is not None:
            tensors.insert(1, _tree_bits_rows(tree_mask, G, Hkv, bq, n_q))
            in_specs.insert(1, pl.BlockSpec((1, bq, _LANES), _paged_q_map))
        out, lse = _paged_decode_call(
            _flash_decode_paged_kernel,
            dict(scale=sm, causal=causal, tq=Tq, block_q=bq, block_k=blk,
                 n_kv_heads=Hkv, tree=tree_mask is not None,
                 block_scales=True),
            tensors,
            in_specs,
            q_offset=q_offset, kv_offset=kv_offset,
            block_table=block_table, batch=B, n_q=n_q, bq=bq, d=D,
            out_dtype=jnp.bfloat16, interpret=interpret,
        )
        out = out[:, :r].reshape(B, Hq, Tq, D).astype(out_dtype)
        lse = lse[:, :r, 0].reshape(B, Hq, Tq)
        return out, lse
    if k_scale.shape != (B, Hkv, 1, D) or v_scale.shape != (B, Hkv, 1, D):
        raise ValueError(
            f"scales must be (B, Hkv, 1, D) = {(B, Hkv, 1, D)}, got "
            f"{k_scale.shape}/{v_scale.shape}"
        )
    # block_size=None falls through to the base kernel, which resolves it
    # from the q8 tile table when K/V are int8 (the one home of that
    # default).
    # Fold K's per-channel scale into Q: (q ⊙ k_s)·k_qᵀ == q·(k_q ⊙ k_s)ᵀ.
    # The fold runs in f32; the folded Q is carried bf16 into the kernel
    # (the MXU fast path, and the same operand precision the unquantized
    # bf16 decode runs at).
    qf = (
        q.astype(jnp.float32).reshape(B, Hkv, G * Tq, D) * k_scale
    ).astype(jnp.bfloat16).reshape(B, Hq, Tq, D)
    # The base split-KV kernel runs the int8 K/V directly (in-kernel bf16
    # casts, exact for [-127, 127]; no dequant multiplies on the KV stream).
    # A block_table passes straight through: the base kernel's paged path
    # streams int8 pool blocks the same way.
    out, lse = attention_pallas_decode(
        qf, k_q, v_q, causal=causal, scale=scale,
        q_offset=q_offset, kv_offset=kv_offset, block_size=block_size,
        interpret=interpret, block_table=block_table, tree_mask=tree_mask,
    )
    # V's per-channel scale applies to the normalised accumulator.
    out = (
        out.astype(jnp.float32).reshape(B, Hkv, G * Tq, D) * v_scale
    ).reshape(B, Hq, Tq, D).astype(q.dtype)
    return out, lse


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_size", "interpret"),
)
def attention_pallas_decode_q8q(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
    block_table: Optional[jax.Array] = None,
    tree_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """int8-MXU flash decode over an int8 KV buffer: Q quantized too.

    Same contract and cache format as :func:`attention_pallas_decode_q8`,
    one step further down the precision/bandwidth trade: K's channel scale
    and the softmax scale fold into Q in f32, then each packed query ROW is
    absmax-quantized to int8, the score matmul runs natively
    int8 x int8 -> int32 on the MXU (no per-tile K dequant cast — the cast
    kernel's dominant VPU cost), and the int32 scores are rescaled by the
    per-row Q scale. Measured 92% of the int8 roofline at 64k ctx vs 86%
    for the cast kernel; adds ~1/254 relative Q-rounding error to the
    logits on top of q8's K error (measured max 0.7% relative output
    error; see measurements/r3/experiment_q8q.jsonl).
    """
    B, Hq, Tq, D = q.shape
    Hkv = k_q.shape[1]
    # Paged: k_q/v_q are (N, Hkv, block, D) pools; the logical context is
    # the table width in blocks (see attention_pallas_decode).
    Tk = (
        block_table.shape[1] * k_q.shape[2] if block_table is not None
        else k_q.shape[2]
    )
    if k_q.dtype != jnp.int8 or v_q.dtype != jnp.int8:
        raise ValueError(
            f"k_q/v_q must be int8, got {k_q.dtype}/{v_q.dtype}"
        )
    # Per-BLOCK scale scalars (ISSUE 13): (N, Hkv) — one dequant scalar
    # per pool block per head, riding block-indexed lane-broadcast
    # operands into the kernel. Only meaningful with a block table; the
    # contiguous shape keeps the per-slot (B, Hkv, 1, D) channel scales
    # (which fold into Q — a per-block scale cannot, it varies along
    # the KV stream).
    per_block = block_table is not None and getattr(k_scale, "ndim", 4) == 2
    if per_block:
        N = k_q.shape[0]
        if k_scale.shape != (N, Hkv) or v_scale.shape != (N, Hkv):
            raise ValueError(
                f"per-block scales must be (N, Hkv) = {(N, Hkv)}, got "
                f"{k_scale.shape}/{v_scale.shape}"
            )
    elif k_scale.shape != (B, Hkv, 1, D) or v_scale.shape != (B, Hkv, 1, D):
        raise ValueError(
            f"scales must be (B, Hkv, 1, D) = {(B, Hkv, 1, D)}, got "
            f"{k_scale.shape}/{v_scale.shape}"
        )
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    sm = (D ** -0.5) if scale is None else scale
    if tree_mask is not None:
        if not causal:
            raise ValueError("tree_mask requires causal=True")
        if Tq > 32:
            raise ValueError(
                f"tree_mask packs into int32 bitmasks: Tq={Tq} exceeds 32"
            )
        if tree_mask.shape != (B, Tq, Tq):
            raise ValueError(
                f"tree_mask must be (B, Tq, Tq) = {(B, Tq, Tq)}, got "
                f"{tree_mask.shape}"
            )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out_dtype = q.dtype

    if Tk == 0:
        return (
            jnp.zeros(q.shape, out_dtype),
            jnp.full((B, Hq, Tq), NEG_INF, jnp.float32),
        )

    # Fold the scales into Q in f32, then per-row absmax int8 quantize
    # (the one q8 numeric contract, quantize_symmetric_int8, reduced over
    # the head-dim axis) — the row scale rides a separate (bq, LANES)
    # input into the kernel. Per-block K scales cannot fold (they vary
    # along the KV stream): only the softmax scale folds, and the
    # kernel's post-matmul rescale picks up each block's scalar.
    r = G * Tq
    qf = q.astype(jnp.float32).reshape(B, Hkv, r, D) * (
        sm if per_block else (k_scale * sm)
    )
    q_i, qs = quantize_symmetric_int8(qf, axis=3)

    bq = min(-(-r // 8) * 8, 128)
    qp = _pad_dim(q_i, 2, bq).reshape(B * Hkv, -1, D)
    n_q = qp.shape[1] // bq
    # Padded rows get scale 0 — their int32 scores then rescale to exactly
    # 0 everywhere, a harmless finite value (the host slices those rows
    # away; under causality they alias a real row's mask anyway).
    qsp = jnp.broadcast_to(
        _pad_dim(qs, 2, bq).reshape(B * Hkv, n_q * bq, 1),
        (B * Hkv, n_q * bq, _LANES),
    )

    if block_table is not None:
        if obs.REGISTRY.enabled:
            _KERNEL_BUILDS.labels(kernel="paged_q8q").inc()
        blk = k_q.shape[2]
        tensors = [qp, qsp, k_q, v_q]
        in_specs = [
            pl.BlockSpec((1, bq, D), _paged_q_map),
            pl.BlockSpec((1, bq, _LANES), _paged_q_map),
            pl.BlockSpec((1, 1, blk, D), _paged_kv_map(Hkv)),
            pl.BlockSpec((1, 1, blk, D), _paged_kv_map(Hkv)),
        ]
        if per_block:
            tensors += [
                _block_scale_rows(k_scale, block_table),
                _block_scale_rows(v_scale, block_table),
            ]
            in_specs += [
                pl.BlockSpec((1, 1, _LANES), _paged_scale_map),
                pl.BlockSpec((1, 1, _LANES), _paged_scale_map),
            ]
        if tree_mask is not None:
            tensors.insert(2, _tree_bits_rows(tree_mask, G, Hkv, bq, n_q))
            in_specs.insert(
                2, pl.BlockSpec((1, bq, _LANES), _paged_q_map)
            )
        out, lse = _paged_decode_call(
            _flash_decode_paged_q8q_kernel,
            dict(causal=causal, tq=Tq, block_q=bq, block_k=blk,
                 n_kv_heads=Hkv, tree=tree_mask is not None,
                 block_scales=per_block),
            tensors,
            in_specs,
            q_offset=q_offset, kv_offset=kv_offset,
            block_table=block_table, batch=B, n_q=n_q, bq=bq, d=D,
            out_dtype=jnp.bfloat16, interpret=interpret,
        )
        out = out[:, :r]
        if per_block:
            # V dequant already happened in-kernel (per-block scalars
            # fold into p); no per-channel epilogue remains.
            out = out.reshape(B, Hq, Tq, D).astype(out_dtype)
        else:
            out = (
                out.astype(jnp.float32).reshape(B, Hkv, r, D) * v_scale
            ).reshape(B, Hq, Tq, D).astype(out_dtype)
        lse = lse[:, :r, 0].reshape(B, Hq, Tq)
        return out, lse

    if block_size is None:
        from tree_attention_tpu.ops.tuning import decode_block_k_q8

        block_size = decode_block_k_q8(Tk)
    bk = min(block_size, max(Tk, _LANES))
    kp = k_q.reshape(B * Hkv, Tk, D)
    vp = v_q.reshape(B * Hkv, Tk, D)
    n_s = -(-Tk // bk)

    offs = _offsets_smem(q_offset, kv_offset, B)

    if obs.REGISTRY.enabled:
        _KERNEL_BUILDS.labels(kernel="q8q").inc()
    tensors = [offs, qp, qsp, kp, vp]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, D), lambda bh, qi, si: (bh, qi, 0)),
        pl.BlockSpec((1, bq, _LANES), lambda bh, qi, si: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, si: (bh, si, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, si: (bh, si, 0)),
    ]
    if tree_mask is not None:
        tensors.insert(3, _tree_bits_rows(tree_mask, G, Hkv, bq, n_q))
        in_specs.insert(
            3,
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, si: (bh, qi, 0)),
        )
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_decode_q8q_kernel,
            causal=causal, tk=Tk, tq=Tq, block_q=bq, block_k=bk,
            n_kv_heads=Hkv, tree=tree_mask is not None,
        ),
        grid=(B * Hkv, n_q, n_s),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi, si: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, si: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, n_q * bq, D), jnp.bfloat16),
            jax.ShapeDtypeStruct((B * Hkv, n_q * bq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*tensors)

    out = out[:, :r]
    # V's per-channel scale on the normalised accumulator, like the q8 path.
    out = (
        out.astype(jnp.float32).reshape(B, Hkv, r, D) * v_scale
    ).reshape(B, Hq, Tq, D).astype(out_dtype)
    lse = lse[:, :r, 0].reshape(B, Hq, Tq)
    return out, lse


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_size", "interpret", "local_blocks",
    ),
)
def attention_pallas_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
    block_table: Optional[jax.Array] = None,
    tree_mask: Optional[jax.Array] = None,
    local_blocks: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Split-KV flash decode. Same ``(out, lse)`` contract as the other impls.

    Intended for Tq < 128 (the decode/speculative regime); any Tq works but
    the Q-tiled training kernel
    (:func:`tree_attention_tpu.ops.pallas_attention.attention_pallas_fwd`)
    is the right shape for large Tq. ``interpret=None`` auto-selects:
    compiled on TPU, interpreter elsewhere (what CI exercises on CPU).

    ``q_offset`` (and ``kv_offset``) may be a scalar or a ``(B,)`` vector —
    the ragged-batch shape: each batch row is a cache slot with its own
    filled length, and the causal mask hides every row's unwritten future
    independently (offsets ride SMEM; the grid and tiles are unchanged).

    With ``block_table`` (a ``(B, NB)`` int32 array) the call is **paged**:
    ``k``/``v`` are ``(N, Hkv, block, D)`` pools and batch row ``b``'s
    logical KV block ``j`` lives in pool row ``block_table[b, j]``. The
    table rides scalar prefetch, the index maps dereference it, and the
    split-KV tile IS the pool block (``block_size`` is ignored — one grid
    step per logical block; on a real TPU keep the pool block >= the
    dtype's min sublane tile, 8/16/32 for f32/bf16/int8). Every entry
    must be a valid pool index; entries past a slot's length are masked
    but still dereferenced (the engine keeps them at 0). Bit-exact with
    gathering ``pool[table]`` into a contiguous buffer and calling the
    unpaged kernel — the tiles stream identical rows in identical order.

    ``tree_mask`` (a ``(B, Tq, Tq)`` bool array; requires ``causal`` and
    ``Tq <= 32``) switches on the speculative tree-verification window
    rule (see :func:`_decode_visibility_mask`): it is packed into int32
    per-row bitmasks that ride a lane-broadcast VMEM operand, exactly
    like the q8q per-row Q scales.

    ``local_blocks`` (ISSUE 18, requires ``block_table``): the table is a
    SIGNED per-shard local view — negative entries mark logical blocks
    owned by other shards of a sequence-sharded pool. Those grid steps
    clamp their DMA to row 0 and the body culls them, so the returned
    ``(out, lse)`` is this shard's flash PARTIAL over its own blocks
    (rows with no local blocks emit the ``(0, -inf)`` merge identity).
    """
    B, Hq, Tq, D = q.shape
    if local_blocks and block_table is None:
        raise ValueError("local_blocks requires block_table")
    if tree_mask is not None:
        if not causal:
            raise ValueError("tree_mask requires causal=True")
        if Tq > 32:
            raise ValueError(
                f"tree_mask packs into int32 bitmasks: Tq={Tq} exceeds 32"
            )
        if tree_mask.shape != (B, Tq, Tq):
            raise ValueError(
                f"tree_mask must be (B, Tq, Tq) = {(B, Tq, Tq)}, got "
                f"{tree_mask.shape}"
            )
    if block_table is not None:
        Hkv, Tk = k.shape[1], block_table.shape[1] * k.shape[2]
    else:
        Hkv, Tk = k.shape[1], k.shape[2]
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of kv heads ({Hkv})"
        )
    G = Hq // Hkv
    s = (D ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out_dtype = q.dtype
    if k.dtype == jnp.int8 and q.dtype != jnp.bfloat16:
        # The kernel casts int8 KV tiles to bf16 in-VMEM (exact for
        # [-127, 127]); a non-bf16 q would make the score dot mixed-dtype
        # and fail at trace time. Callers normally arrive via the q8
        # wrapper, which folds scales into q in f32 and emits bf16; direct
        # callers get the same operand precision applied here (ADVICE r2),
        # with the output returned in their original dtype.
        q = q.astype(jnp.bfloat16)

    if Tk == 0:
        return (
            jnp.zeros(q.shape, out_dtype),  # not zeros_like: q may be the
            jnp.full((B, Hq, Tq), NEG_INF, jnp.float32),  # bf16-cast copy
        )

    # Pack each KV head's queries (its whole GQA group × Tq rows) into the
    # Q-tile sublanes: (B, Hq, Tq, D) -> (B·Hkv, r8, D).
    r = G * Tq
    bq = min(-(-r // 8) * 8, 128)
    qp = _pad_dim(q.reshape(B, Hkv, r, D), 2, bq).reshape(B * Hkv, -1, D)
    n_q = qp.shape[1] // bq

    if block_table is not None:
        if obs.REGISTRY.enabled:
            _KERNEL_BUILDS.labels(
                kernel="paged_q8" if k.dtype == jnp.int8 else "paged"
            ).inc()
        tensors = [qp, k, v]
        kv_map = _paged_kv_map(Hkv, local=local_blocks)
        in_specs = [
            pl.BlockSpec((1, bq, D), _paged_q_map),
            pl.BlockSpec((1, 1, k.shape[2], D), kv_map),
            pl.BlockSpec((1, 1, k.shape[2], D), kv_map),
        ]
        if tree_mask is not None:
            tensors.insert(1, _tree_bits_rows(tree_mask, G, Hkv, bq, n_q))
            in_specs.insert(
                1, pl.BlockSpec((1, bq, _LANES), _paged_q_map)
            )
        out, lse = _paged_decode_call(
            _flash_decode_paged_kernel,
            dict(scale=s, causal=causal, tq=Tq, block_q=bq,
                 block_k=k.shape[2], n_kv_heads=Hkv,
                 tree=tree_mask is not None,
                 local_blocks=local_blocks),
            tensors,
            in_specs,
            q_offset=q_offset, kv_offset=kv_offset,
            block_table=block_table, batch=B, n_q=n_q, bq=bq, d=D,
            out_dtype=q.dtype, interpret=interpret,
        )
        out = out[:, :r].reshape(B, Hq, Tq, D).astype(out_dtype)
        lse = lse[:, :r, 0].reshape(B, Hq, Tq)
        return out, lse

    if block_size is None:
        from tree_attention_tpu.ops.tuning import decode_block_k, decode_block_k_q8

        # Direct int8 callers (the q8 wrapper normally resolves first) get
        # the q8 table: half the bytes per tile leaves the exact path's tile
        # size overhead-bound (measured 76.3% vs 85.2% of the int8 roofline
        # at 64k).
        block_size = (
            decode_block_k_q8(Tk) if k.dtype == jnp.int8 else decode_block_k(Tk)
        )

    # No host-side KV padding: Pallas handles a ragged last block itself and
    # the kernel's ``col_idx < tk`` mask drops the garbage columns. An
    # explicit jnp.pad here would copy the ENTIRE KV buffer every decode step
    # whenever Tk % bk != 0 — measured as the difference between 27% and 92%
    # of the HBM roofline on the reference's 64000-token workload.
    bk = min(block_size, max(Tk, _LANES))
    kp = k.reshape(B * Hkv, Tk, D)
    vp = v.reshape(B * Hkv, Tk, D)
    n_s = -(-Tk // bk)

    offs = _offsets_smem(q_offset, kv_offset, B)

    if obs.REGISTRY.enabled:
        # int8 operands here are the q8 (bf16-cast) path riding the base
        # kernel; the q8q wrapper has its own pallas_call and label.
        _KERNEL_BUILDS.labels(
            kernel="q8" if k.dtype == jnp.int8 else "exact"
        ).inc()
    tensors = [offs, qp, kp, vp]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, D), lambda bh, qi, si: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, si: (bh, si, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, si: (bh, si, 0)),
    ]
    if tree_mask is not None:
        tensors.insert(2, _tree_bits_rows(tree_mask, G, Hkv, bq, n_q))
        in_specs.insert(
            2,
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, si: (bh, qi, 0)),
        )
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_decode_kernel,
            scale=s, causal=causal, tk=Tk, tq=Tq, block_q=bq, block_k=bk,
            n_kv_heads=Hkv, tree=tree_mask is not None,
        ),
        grid=(B * Hkv, n_q, n_s),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi, si: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, si: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, n_q * bq, D), q.dtype),
            jax.ShapeDtypeStruct((B * Hkv, n_q * bq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        # Only the split-KV dim is sequential (carried online-softmax state);
        # batch-head and Q-tile dims can split across megacore parts.
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*tensors)

    out = out[:, :r].reshape(B, Hq, Tq, D).astype(out_dtype)
    lse = lse[:, :r, 0].reshape(B, Hq, Tq)
    return out, lse
