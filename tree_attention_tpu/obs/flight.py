"""Tick flight recorder: a bounded ring of per-tick serving records.

The serving engine's aggregate metrics say *how much*; the span trace says
*how long* — neither answers "what was the engine doing when it wedged?"
after the process is gone. This is the black box: every tick the engine
appends one small structured record (occupancy, slot states, chunk plan,
tokens emitted, whether the tick paid the host sync, queue depth, wall
time) to a fixed-capacity ring. Cost is O(1) per tick and bounded memory
forever; the ring holds the LAST ``capacity`` ticks — exactly the window a
post-mortem needs.

Dump triggers (any of):

- **on demand** — the ``/flight`` HTTP endpoint or :meth:`snapshot`;
- **on engine error** — ``SlotServer.serve`` dumps before re-raising;
- **on SIGTERM / SIGUSR1 / atexit** — :func:`obs.install_crash_handlers
  <tree_attention_tpu.obs.install_crash_handlers>` flushes the armed sink
  (``--flight-out`` / ``TA_FLIGHT_OUT``), so a killed or wedged run still
  leaves its last ticks on disk.

Liveness: :meth:`last_tick_age` is the seconds since the engine last
recorded a tick — the ``/healthz`` endpoint's truth (a serving process
whose ring stopped moving is wedged even if the HTTP thread still
answers).

Disabled (the default) is free: :meth:`record` is one attribute check and
an early return; call sites must build their record dict only under an
``if FLIGHT.enabled:`` guard — the same contract as span args.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Fixed-capacity ring of per-tick records; disarmed until enabled."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        # Reentrant: the SIGTERM/SIGUSR1 flush runs on the main thread and
        # may interrupt a record() holding this lock; a plain Lock would
        # deadlock dump_if_armed instead of dumping.
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=capacity)
        self._ticks_recorded = 0
        self._last_tick_t: Optional[float] = None
        self._idle = True
        self._dump_path: Optional[str] = None
        self.enabled = False

    # -- lifecycle --------------------------------------------------------

    def arm(self, dump_path: Optional[str] = None,
            capacity: Optional[int] = None) -> None:
        """Enable recording; ``dump_path`` is where crash/error/signal
        dumps land (``None`` keeps the ring memory-only — ``/flight`` and
        :meth:`snapshot` still serve it)."""
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(capacity, 1))
            self._dump_path = dump_path
        self.enabled = True

    def disarm(self) -> None:
        self.enabled = False
        with self._lock:
            self._dump_path = None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._ticks_recorded = 0
            self._last_tick_t = None
            self._idle = True

    @property
    def dump_path(self) -> Optional[str]:
        return self._dump_path

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    @property
    def ticks_recorded(self) -> int:
        return self._ticks_recorded

    # -- recording --------------------------------------------------------

    def record(self, rec: Optional[Dict[str, Any]]) -> None:
        """Append one per-tick record. The dict is the caller's — built
        only under ``if FLIGHT.enabled:`` so the disabled path allocates
        nothing (``record(None)`` when disabled is the no-op fast path)."""
        if not self.enabled or rec is None:
            return
        now = time.monotonic()
        with self._lock:
            self._ring.append(rec)
            self._ticks_recorded += 1
            self._last_tick_t = now
            self._idle = False

    def mark_idle(self) -> None:
        """Declare the tick loop drained (a serve() run completed): the
        engine is between runs, not wedged — ``/healthz`` must not count
        a finished run's age as a stall. The ring and liveness timestamp
        survive for post-mortems; the next record() clears idleness."""
        with self._lock:
            self._idle = True

    @property
    def idle(self) -> bool:
        return self._idle

    def last_tick_age(self) -> Optional[float]:
        """Seconds since the last recorded tick; None before any tick."""
        t = self._last_tick_t
        return None if t is None else max(time.monotonic() - t, 0.0)

    # -- export -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            records: List[Dict[str, Any]] = list(self._ring)
            ticks = self._ticks_recorded
        age = self.last_tick_age()
        return {
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "capacity": self.capacity,
            "ticks_recorded": ticks,
            "last_tick_age_s": None if age is None else round(age, 3),
            "records": records,
        }

    def dump(self, path: str, reason: str = "on_demand") -> None:
        """Write the ring as JSON (creates parent dirs)."""
        snap = self.snapshot()
        snap["reason"] = reason
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, default=str)
            f.write("\n")

    def dump_if_armed(self, reason: str) -> Optional[str]:
        """Dump to the armed sink path, if any — the crash/error hook.
        Never raises (the black box must not kill the workload it
        records); returns the path written or None."""
        path = self._dump_path
        if not self.enabled or not path:
            return None
        try:
            self.dump(path, reason=reason)
            return path
        except OSError:
            return None


#: The process-wide recorder the serving engine feeds.
FLIGHT = FlightRecorder()
