"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Timing (``utils/profiling.py``) answers *how long*; this registry answers
*how much* — tokens decoded, collective payload bytes, kernel dispatches,
watchdog stalls, guard verdicts — the machine-readable vocabulary the
ROADMAP's serving/multi-chip work needs before any run can be trusted.

Design constraints, in order:

1. **Disabled is free.** The registry starts disabled; every mutation
   method's first action is one attribute check and an early return — no
   lock, no dict lookup, no allocation. Hot paths (``host_runtime.heartbeat``
   runs once per fenced timing iteration) stay overhead-free unless the run
   asked for telemetry (``--metrics-out`` / :func:`enable`). The guard test
   in ``tests/test_obs.py`` holds this to "no per-call allocation".
2. **Thread-safe when enabled.** One registry lock serialises mutations and
   snapshots; the native host pipeline and async checkpointing both run
   threads that may touch metrics.
3. **Two export formats.** :meth:`MetricsRegistry.snapshot` is the JSON
   shape (what ``--metrics-out`` writes); :meth:`MetricsRegistry.to_prometheus`
   is the Prometheus text exposition format, so a future serving layer can
   mount it on ``/metrics`` unchanged.

Trace-time semantics: a counter incremented inside code that JAX traces
(anything under ``jax.jit`` / ``shard_map`` / ``lax.scan``) counts *traces*,
not executions — the Python body runs once per compilation. Instrumentation
sites therefore split by layer: host loops (CLI, bench harness, launcher)
count real executions; algorithm entry points (``parallel/*``, ``ops/*``)
count dispatches and the *per-call* payload implied by their static shapes.
Metric help strings say which they are.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default buckets (seconds): decode steps live in the
# 100us-100ms band, host phases (compile, launch) in the 0.1-60s band.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def percentile(sorted_vals: Sequence[float], p: float) -> float:
    """Nearest-rank percentile of an ascending sequence (``0 <= p <= 1``).

    The ONE exact-percentile definition every latency report uses
    (``ServeReport``, the SLO windows, bench records) — duplicated
    nearest-rank variants drift in their rounding and then p95s disagree
    across layers for no physical reason. Empty input returns 0.0 (a
    report with no samples, not an error).
    """
    if not sorted_vals:
        return 0.0
    return sorted_vals[
        min(len(sorted_vals) - 1, int(p * (len(sorted_vals) - 1) + 0.5))
    ]


def _check_name(name: str) -> None:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")


def _check_labels(label_names: Sequence[str]) -> Tuple[str, ...]:
    names = tuple(label_names)
    for n in names:
        if not _LABEL_RE.match(n):
            raise ValueError(f"invalid label name {n!r}")
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate label names in {names}")
    return names


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral floats print as ints (repr round-
    trips everything else)."""
    if isinstance(v, bool):  # bool is an int subclass; be explicit
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared parent/child machinery.

    An unlabeled metric is its own (only) child. A labeled metric is a
    parent: :meth:`labels` resolves/creates the child for one label-value
    tuple, and mutations on the parent itself raise (there is no value to
    mutate). Children cache forever — a bounded label space is the caller's
    contract, same as Prometheus client libraries.
    """

    _type = "untyped"

    __slots__ = (
        "name", "help", "_label_names", "_registry", "_children", "_lock",
    )

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        help: str,
        label_names: Tuple[str, ...],
    ):
        self.name = name
        self.help = help
        self._label_names = label_names
        self._registry = registry
        self._lock = registry._lock
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}
        if not label_names:
            self._init_value()

    def _init_value(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def _make_child(self) -> "_Metric":
        child = type(self).__new__(type(self))
        child.name = self.name
        child.help = self.help
        child._label_names = ()
        child._registry = self._registry
        child._lock = self._lock
        child._children = {}
        self._copy_config(child)
        child._init_value()
        return child

    def _copy_config(self, child: "_Metric") -> None:
        """Hook for subclasses with per-metric config (histogram buckets)."""

    def labels(self, **labels: Any) -> "_Metric":
        """The child for one label-value assignment (created on first use).

        Resolve once and keep the returned child where the call site is hot:
        the child's mutators are the allocation-free fast path; this lookup
        builds a tuple per call.
        """
        if tuple(sorted(labels)) != tuple(sorted(self._label_names)):
            raise ValueError(
                f"metric {self.name!r} takes labels {self._label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        key = tuple(str(labels[n]) for n in self._label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    def _samples(self) -> Iterable[Tuple[Dict[str, str], Any]]:
        """(labels-dict, value-payload) pairs under the registry lock."""
        if not self._label_names:
            yield {}, self._value_payload()
            return
        for key, child in self._children.items():
            yield dict(zip(self._label_names, key)), child._value_payload()

    def _value_payload(self) -> Any:  # pragma: no cover - overridden
        raise NotImplementedError

    def _guard_unlabeled(self) -> None:
        if self._label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled "
                f"({self._label_names}); call .labels(...) first"
            )


class Counter(_Metric):
    """Monotonically increasing count."""

    _type = "counter"
    __slots__ = ("_value",)

    def _init_value(self) -> None:
        self._value = 0

    def inc(self, value: float = 1) -> None:
        if not self._registry._enabled:
            return
        self._guard_unlabeled()
        if value < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._value += value

    def value(self) -> float:
        self._guard_unlabeled()
        return self._value

    def _value_payload(self) -> float:
        return self._value


class Gauge(_Metric):
    """A value that can go up and down (capacities, fill levels, flags)."""

    _type = "gauge"
    __slots__ = ("_value",)

    def _init_value(self) -> None:
        self._value = 0

    def set(self, value: float) -> None:
        if not self._registry._enabled:
            return
        self._guard_unlabeled()
        with self._lock:
            self._value = value

    def inc(self, value: float = 1) -> None:
        if not self._registry._enabled:
            return
        self._guard_unlabeled()
        with self._lock:
            self._value += value

    def dec(self, value: float = 1) -> None:
        self.inc(-value)

    def value(self) -> float:
        self._guard_unlabeled()
        return self._value

    def _value_payload(self) -> float:
        return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram (per-bucket counts + sum + count).

    Buckets are upper bounds; an implicit ``+Inf`` bucket catches the rest.
    Internally counts are per-band; exports are cumulative (the Prometheus
    ``le`` convention), which the JSON shape mirrors so the two formats
    round-trip against each other.
    """

    _type = "histogram"
    __slots__ = ("_buckets", "_counts", "_sum", "_count")

    def __init__(self, registry, name, help, label_names, buckets):
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        if len(set(b)) != len(b):
            raise ValueError(f"histogram {name!r} has duplicate buckets {b}")
        self._buckets = b
        super().__init__(registry, name, help, label_names)

    def _init_value(self) -> None:
        self._counts = [0] * (len(self._buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def _copy_config(self, child: "_Metric") -> None:
        child._buckets = self._buckets  # shared, immutable

    def observe(self, value: float) -> None:
        if not self._registry._enabled:
            return
        self._guard_unlabeled()
        idx = bisect_left(self._buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def _value_payload(self) -> Dict[str, Any]:
        cum, total = [], 0
        for le, c in zip(self._buckets, self._counts):
            total += c
            cum.append([le, total])
        cum.append(["+Inf", self._count])
        return {"count": self._count, "sum": self._sum, "buckets": cum}

    def quantile(self, p: float) -> float:
        """Estimate the ``p``-quantile (``0 <= p <= 1``) from the bucket
        counts — monotone linear interpolation inside the target bucket,
        the same model ``histogram_quantile`` applies to a Prometheus
        scrape, so a live dashboard and this in-process value agree.

        The first bucket interpolates from 0 (these are latency-shaped
        metrics); a quantile landing in the ``+Inf`` bucket clamps to the
        highest finite bound (there is no upper edge to interpolate
        toward). Returns 0.0 for an empty histogram.
        """
        self._guard_unlabeled()
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"quantile p must be in [0, 1], got {p}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = p * total
        cum = 0
        for i, c in enumerate(counts[:-1]):
            if cum + c >= target and c > 0:
                lo = self._buckets[i - 1] if i > 0 else 0.0
                hi = self._buckets[i]
                return lo + (hi - lo) * (target - cum) / c
            cum += c
        return self._buckets[-1]


class MetricsRegistry:
    """Process-wide metric store; starts disabled (mutations are no-ops).

    Metric registration is idempotent: re-declaring the same (name, type,
    labels) returns the existing object — module-level instrumentation can
    declare its metrics at import without coordination — while a conflicting
    redeclaration raises.
    """

    def __init__(self, enabled: bool = False):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._enabled = bool(enabled)

    # -- enablement -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        with self._lock:  # cold path; reads stay lock-free via .enabled
            self._enabled = True

    def disable(self) -> None:
        with self._lock:
            self._enabled = False

    # -- registration -----------------------------------------------------

    def _register(self, cls, name, help, label_names, **kw) -> _Metric:
        _check_name(name)
        labels = _check_labels(label_names)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (
                    type(existing) is not cls
                    or existing._label_names != labels
                    or (
                        cls is Histogram
                        and kw
                        and existing._buckets
                        != tuple(sorted(float(x) for x in kw["buckets"]))
                    )
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing._type} with labels "
                        f"{existing._label_names}"
                    )
                return existing
            metric = cls(self, name, help, labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help, labels)  # type: ignore

    def gauge(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help, labels)  # type: ignore

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, name, help, labels, buckets=buckets
        )  # type: ignore

    # -- export -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of every metric (the ``--metrics-out`` payload)."""
        from tree_attention_tpu.utils.logging import _process_index

        out: List[Dict[str, Any]] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                samples = [
                    {"labels": lbls, **(
                        v if isinstance(v, dict) else {"value": v}
                    )}
                    for lbls, v in m._samples()
                ]
                out.append({
                    "name": m.name, "type": m._type, "help": m.help,
                    "samples": samples,
                })
        return {
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "process_index": _process_index(),
            "metrics": out,
        }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def write_json(self, path: str) -> None:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))
            f.write("\n")

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m._type}")
                for lbls, payload in m._samples():
                    if isinstance(payload, dict):  # histogram
                        for le, c in payload["buckets"]:
                            lines.append(
                                f"{m.name}_bucket"
                                f"{_label_str({**lbls, 'le': _fmt_le(le)})}"
                                f" {c}"
                            )
                        lines.append(
                            f"{m.name}_sum{_label_str(lbls)} "
                            f"{_fmt_value(payload['sum'])}"
                        )
                        lines.append(
                            f"{m.name}_count{_label_str(lbls)} "
                            f"{payload['count']}"
                        )
                    else:
                        lines.append(
                            f"{m.name}{_label_str(lbls)} "
                            f"{_fmt_value(payload)}"
                        )
        return "\n".join(lines) + "\n"

    # -- test support -----------------------------------------------------

    def reset(self) -> None:
        """Zero every value (keeps registrations). For tests."""
        with self._lock:
            for m in self._metrics.values():
                if not m._label_names:
                    m._init_value()
                for child in m._children.values():
                    child._init_value()

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)


def _fmt_le(le: Any) -> str:
    return le if isinstance(le, str) else _fmt_value(le)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


#: The process-wide default registry every instrumentation site uses.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(
    name: str,
    help: str = "",
    labels: Sequence[str] = (),
    buckets: Sequence[float] = DEFAULT_BUCKETS,
) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets)
