"""Per-request cost ledger: where did request X's time (and KV) go?

The metrics registry aggregates (counters, histograms) and the tracer
records spans, but neither answers the per-request question — "this one
request: how long did it queue, prefill, park in handoff, decode; how
many tokens did it reuse from the prefix cache; how many device
block-seconds did its KV hold; did spec decoding pay off for it" —
without hand-joining artifacts. DistServe (arXiv:2401.09670) drives its
placement decisions from exactly this per-phase attribution; ROADMAP
item 4 (self-tuning scheduler) will read the same substrate.

One :class:`RequestLedger` per request uid, accumulated at the engine's
existing one-admit/one-retire seams (plus the disagg park/adopt seam),
kept in a live table while the request runs and moved to a bounded ring
of recent completions at retire. Exported three ways:

- the SSE ``usage`` block (ingress attaches the finished ledger);
- ``ServeReport.requests`` aggregates (:func:`aggregate_ledgers` over
  the run's finished ledgers — pure, no global state);
- the obs HTTP server's ``/requests`` and ``/request/{uid}`` endpoints
  (live + ring snapshots from :data:`REQLOG`).

Disabled (the default) is free: every method early-returns on one
attribute check and call sites guard with ``if REQLOG.enabled:`` before
building any payload — the same zero-allocation contract as the metrics
registry and tracer, machine-enforced by the obs-guard lint pass (this
file is the one ``obs/`` module IN its scope). All shared state mutates
under one re-entrant ``self._lock`` (lock-safety pass): the live table
and ring are read by HTTP handler threads while the engine thread
writes them.

Wall-segment semantics (the reconciliation contract): for a finished
ledger, ``prefill_s + handoff_s + decode_s`` equals the request span's
duration (admit → retire) to within one tick, and ``queue_wait_s`` is
the pre-span wait. With ``n>1`` sampling the uid's ledger is closed by
the first branch that retires (branch-level attribution is out of
scope — the ledger is per-request).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from tree_attention_tpu.obs.tracing import TRACER

DEFAULT_RING = 256

#: Integer accumulator fields note() accepts (anything else is a bug).
_COUNTERS = (
    "host_demotes", "host_restores", "spec_proposed", "spec_accepted",
    "fork_shared_blocks",
)


class RequestLedger:
    """Mutable per-request cost record; one per uid, engine-thread owned
    while live (readers go through :meth:`ReqLog.snapshot` copies)."""

    __slots__ = (
        "uid", "trace_id", "span_id", "parent_span_id", "phase",
        "arrival_tick", "admit_tick", "finish_tick", "outcome",
        "prompt_tokens", "prefix_hit_tokens", "tokens_prefilled",
        "tokens_decoded",
        "queue_wait_s", "prefill_s", "handoff_s", "decode_s",
        "kv_block_seconds", "host_demotes", "host_restores",
        "spec_proposed", "spec_accepted", "fork_shared_blocks",
        "_t_admit", "_t_first", "_t_park", "_blk_n", "_blk_t",
    )

    def __init__(self, uid: int, now: float):
        self.uid = uid
        self.trace_id = ""
        self.span_id = ""
        self.parent_span_id = ""
        self.phase = "prefill"
        self.arrival_tick = 0
        self.admit_tick = 0
        self.finish_tick = -1
        self.outcome = ""  # empty while live
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.tokens_prefilled = 0
        self.tokens_decoded = 0
        self.queue_wait_s = 0.0
        self.prefill_s = 0.0
        self.handoff_s = 0.0
        self.decode_s = 0.0
        self.kv_block_seconds = 0.0
        self.host_demotes = 0
        self.host_restores = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.fork_shared_blocks = 0
        self._t_admit = now
        self._t_first = -1.0
        self._t_park = -1.0
        self._blk_n = 0
        self._blk_t = now

    # -- derived views ----------------------------------------------------

    def wall_s(self, now: Optional[float] = None) -> float:
        """Admit → retire (or → now while live); the request span's dur."""
        end = now if now is not None else self._blk_t
        return max(0.0, end - self._t_admit)

    def as_dict(self, now: Optional[float] = None) -> Dict[str, Any]:
        live = self.outcome == ""
        t = time.monotonic() if (live and now is None) else now
        d: Dict[str, Any] = {
            "uid": self.uid,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "phase": self.phase,
            "outcome": self.outcome or None,
            "arrival_tick": self.arrival_tick,
            "admit_tick": self.admit_tick,
            "finish_tick": self.finish_tick,
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "tokens_prefilled": self.tokens_prefilled,
            "tokens_decoded": self.tokens_decoded,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "prefill_s": round(self.prefill_s, 6),
            "handoff_s": round(self.handoff_s, 6),
            "decode_s": round(self.decode_s, 6),
            "wall_s": round(self.wall_s(t), 6),
            "kv_block_seconds": round(self.kv_block_seconds, 6),
            "host_demotes": self.host_demotes,
            "host_restores": self.host_restores,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "fork_shared_blocks": self.fork_shared_blocks,
        }
        d["phases"] = [
            {"phase": "queue", "wall_s": d["queue_wait_s"]},
            {"phase": "prefill", "wall_s": d["prefill_s"]},
            {"phase": "handoff", "wall_s": d["handoff_s"]},
            {"phase": "decode", "wall_s": d["decode_s"]},
        ]
        return d


class ReqLog:
    """Process-wide ledger table: live requests + a ring of recent
    completions. Disarmed (the default) every method is one attribute
    check; armed, mutations happen under the re-entrant lock (HTTP
    handler threads snapshot while the engine thread writes)."""

    def __init__(self, ring: int = DEFAULT_RING):
        # RLock, not Lock: snapshot() is called from HTTP handler threads
        # while finish() may be emitting under TRACER's own lock — and the
        # crash handlers may interrupt either; re-entrancy keeps the
        # flush-then-die contract deadlock-free (same reasoning as the
        # tracer and registry locks).
        self._lock = threading.RLock()
        self._live: Dict[int, RequestLedger] = {}
        self._ring: deque = deque(maxlen=ring)
        self.enabled = False

    # -- lifecycle --------------------------------------------------------

    def arm(self, ring: Optional[int] = None) -> None:
        with self._lock:
            if ring is not None and ring != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=ring)
            self.enabled = True

    def disarm(self) -> None:
        """Stop recording and drop state (a later run arms afresh)."""
        with self._lock:
            self.enabled = False
            self._live.clear()
            self._ring.clear()

    # -- accumulation seams (engine thread) -------------------------------

    def open(
        self,
        uid: int,
        *,
        trace_id: str = "",
        span_id: str = "",
        parent_span_id: str = "",
        prompt_tokens: int = 0,
        prefix_hit_tokens: int = 0,
        arrival_tick: int = 0,
        admit_tick: int = 0,
        queue_wait_s: float = 0.0,
        nblocks: int = 0,
        now: Optional[float] = None,
    ) -> None:
        """Open a ledger at the engine's one-admit-path seam."""
        if not self.enabled:
            return
        t = time.monotonic() if now is None else now
        led = RequestLedger(uid, t)
        led.trace_id = trace_id
        led.span_id = span_id
        led.parent_span_id = parent_span_id
        led.prompt_tokens = prompt_tokens
        led.prefix_hit_tokens = prefix_hit_tokens
        led.tokens_prefilled = max(0, prompt_tokens - prefix_hit_tokens)
        led.arrival_tick = arrival_tick
        led.admit_tick = admit_tick
        led.queue_wait_s = queue_wait_s
        led._blk_n = nblocks
        with self._lock:
            self._live[uid] = led

    def note(self, uid: int, **deltas: int) -> None:
        """Accumulate integer counters (``spec_proposed=4``, …)."""
        if not self.enabled:
            return
        with self._lock:
            led = self._live.get(uid)
            if led is None:
                return
            for k, v in deltas.items():
                if k in _COUNTERS:
                    setattr(led, k, getattr(led, k) + v)

    def blocks(self, uid: int, n: int, now: Optional[float] = None) -> None:
        """Device-block count changed: integrate block-seconds so far."""
        if not self.enabled:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            led = self._live.get(uid)
            if led is None:
                return
            led.kv_block_seconds += led._blk_n * max(0.0, t - led._blk_t)
            led._blk_n = n
            led._blk_t = t

    def first_token(self, uid: int, now: Optional[float] = None) -> None:
        """First token produced: closes the prefill segment."""
        if not self.enabled:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            led = self._live.get(uid)
            if led is None or led._t_first >= 0.0:
                return
            led._t_first = t
            led.prefill_s = max(0.0, t - led._t_admit)
            led.phase = "decode"

    def park(self, uid: int, now: Optional[float] = None) -> None:
        """Disagg handoff: the prefill worker parked this request."""
        if not self.enabled:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            led = self._live.get(uid)
            if led is None:
                return
            led._t_park = t
            led.phase = "handoff"

    def resume(self, uid: int, now: Optional[float] = None) -> None:
        """Disagg handoff: a decode worker adopted this request."""
        if not self.enabled:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            led = self._live.get(uid)
            if led is None:
                return
            if led._t_park >= 0.0:
                led.handoff_s += max(0.0, t - led._t_park)
                led._t_park = -1.0
            led.phase = "decode"

    def finish(
        self,
        uid: int,
        *,
        outcome: str,
        finish_tick: int = -1,
        tokens_decoded: int = 0,
        nblocks: int = 0,
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Finalize at the one-retire-path seam; returns the finished
        ledger dict (``None`` when disabled or the uid is unknown —
        idempotent for ``n>1`` branch retires after the first)."""
        if not self.enabled:
            return None
        t = time.monotonic() if now is None else now
        with self._lock:
            led = self._live.pop(uid, None)
            if led is None:
                return None
            # Close the block-seconds integral and any open park.
            led.kv_block_seconds += led._blk_n * max(0.0, t - led._blk_t)
            led._blk_n = nblocks
            led._blk_t = t
            if led._t_park >= 0.0:
                led.handoff_s += max(0.0, t - led._t_park)
                led._t_park = -1.0
            if led._t_first < 0.0:
                # Never produced a token: the whole span was prefill.
                led.prefill_s = max(0.0, t - led._t_admit)
                led._t_first = t
            # Decode is the remainder, so the three segments sum to the
            # span duration exactly: wall = prefill + handoff + decode.
            led.decode_s = max(
                0.0,
                (t - led._t_admit) - led.prefill_s - led.handoff_s,
            )
            led.tokens_decoded = tokens_decoded
            led.outcome = outcome
            led.phase = "done"
            led.finish_tick = finish_tick
            out = led.as_dict(t)
            self._ring.append(out)
        if TRACER.active:
            TRACER.instant("request_ledger", cat="serving", args={
                "rid": uid, "trace_id": led.trace_id,
                "outcome": outcome, "decode_s": out["decode_s"],
                "prefill_s": out["prefill_s"],
                "handoff_s": out["handoff_s"],
            })
        return out

    def drop(self, uid: int) -> None:
        """Forget a live ledger without ringing it (rejected pre-admit)."""
        if not self.enabled:
            return
        with self._lock:
            self._live.pop(uid, None)

    # -- read side (HTTP handler threads) ---------------------------------

    def get(self, uid: int) -> Optional[Dict[str, Any]]:
        """Single-ledger view: live first, then the recent ring."""
        with self._lock:
            led = self._live.get(uid)
            if led is not None:
                return led.as_dict()
            for d in reversed(self._ring):
                if d["uid"] == uid:
                    return dict(d)
        return None

    def snapshot(self) -> Dict[str, Any]:
        """``{"live": [...], "recent": [...]}`` — copies, lock released
        before serialization."""
        with self._lock:
            live = [led.as_dict() for led in self._live.values()]
            recent = [dict(d) for d in self._ring]
        live.sort(key=lambda d: d["uid"])
        return {"enabled": self.enabled, "live": live, "recent": recent}


def aggregate_ledgers(
    ledgers: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Run-level aggregates for ``ServeReport.requests`` — pure function
    over finished ledger dicts (no global state, usable disabled → None).
    """
    if not ledgers:
        return None
    n = len(ledgers)
    out: Dict[str, Any] = {"count": n}
    for key in ("queue_wait_s", "prefill_s", "handoff_s", "decode_s",
                "kv_block_seconds"):
        vals = sorted(d.get(key, 0.0) for d in ledgers)
        out[f"{key}_sum"] = round(sum(vals), 6)
        out[f"{key}_p50"] = round(vals[n // 2], 6)
    for key in ("tokens_prefilled", "tokens_decoded", "prefix_hit_tokens",
                "host_demotes", "host_restores", "spec_proposed",
                "spec_accepted", "fork_shared_blocks"):
        out[f"{key}_total"] = sum(int(d.get(key, 0)) for d in ledgers)
    return out


#: The process-wide ledger table every seam records into.
REQLOG = ReqLog()
