"""Live telemetry HTTP endpoint: /metrics, /healthz, /flight, /requests.

PR 1's registry was built so "a future serving layer can mount it on
``/metrics`` unchanged" — this is that layer. A stdlib
:class:`~http.server.ThreadingHTTPServer` on a daemon thread (zero new
dependencies, dies with the process) serving:

- ``/metrics`` — Prometheus text exposition format, byte-identical to
  :meth:`MetricsRegistry.to_prometheus` at scrape time;
- ``/metrics.json`` — the registry snapshot (the same shape
  ``--metrics-out`` writes at exit, but live);
- ``/healthz`` — tick liveness from the flight recorder: 200 while the
  engine is recording ticks (or idle before any tick), 503 once the last
  tick is older than ``stall_after`` — a wedged tick loop fails the check
  even though the HTTP thread still answers (that asymmetry is the point);
- ``/flight`` — the flight recorder ring as JSON, the live post-mortem;
- ``/requests`` — the request ledger (ISSUE 16): live requests with
  their running wall segments plus the bounded ring of recently
  finished ones;
- ``/request/{uid}`` — one request's full ledger (live or recent), with
  its phase timeline — 404 for a uid the ring has already evicted;
- ``/slots`` — per-slot occupancy from the wired engine (state, uid,
  generated length, context length, paged block count); 404 when no
  engine was wired in.

Scrapes hold the registry lock only for the duration of one snapshot —
the same cost an exit dump pays; the engine's disabled-path contract is
untouched (the server only *reads*: ``/slots`` uses the engine's
GIL-atomic snapshot, never a lock the tick loop holds).

Server lifecycle (daemon thread, localhost bind, ``port=0`` OS-pick) is
the shared :class:`~tree_attention_tpu.utils.httpd.DaemonHTTPServer`
plumbing — the serving ingress rides the identical base.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional, Tuple

from tree_attention_tpu.obs.flight import FLIGHT, FlightRecorder
from tree_attention_tpu.obs.metrics import REGISTRY, MetricsRegistry
from tree_attention_tpu.obs.reqlog import REQLOG, ReqLog
from tree_attention_tpu.utils.httpd import DaemonHTTPServer

DEFAULT_STALL_AFTER = 60.0


def flight_health(flight: FlightRecorder,
                  stall_after: float = DEFAULT_STALL_AFTER,
                  ) -> Tuple[int, Dict[str, Any]]:
    """Tick-liveness verdict over one flight recorder: the shared core
    of this server's ``/healthz`` and the fleet router's federated
    health roll-up (a wedged replica must fail the FLEET check, not
    just its own process's — ISSUE 16 satellite)."""
    age = flight.last_tick_age()
    body: Dict[str, Any] = {
        "ticks_recorded": flight.ticks_recorded,
        "last_tick_age_s": None if age is None else round(age, 3),
        "stall_after_s": stall_after,
    }
    if age is None or flight.idle:
        # No tick yet, or the engine drained its run and said so
        # (mark_idle) — alive between runs, however long ago the last
        # tick was. Only a loop that stopped WITHOUT draining stalls.
        body["status"] = "idle"
        return 200, body
    if age <= stall_after:
        body["status"] = "ok"
        return 200, body
    body["status"] = "stalled"
    return 503, body


class MetricsHTTPServer(DaemonHTTPServer):
    """Daemon-thread HTTP exporter over one registry + flight recorder.

    ``engine`` (optional) is anything with a ``slots_snapshot()``
    method — a :class:`SlotServer` or :class:`DisaggServer` — backing
    ``/slots``; ``reqlog`` backs ``/requests`` and ``/request/{uid}``.
    """

    thread_name = "obs-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        registry: MetricsRegistry = REGISTRY,
        flight: FlightRecorder = FLIGHT,
        reqlog: ReqLog = REQLOG,
        engine: Optional[Any] = None,
        stall_after: float = DEFAULT_STALL_AFTER,
    ):
        super().__init__(port, host)
        self._registry = registry
        self._flight = flight
        self._reqlog = reqlog
        self._engine = engine
        self._stall_after = stall_after

    def attach_engine(self, engine: Any) -> None:
        """Late-wire the engine backing ``/slots`` — the CLI starts this
        exporter before it builds the engine, so the wiring is a second
        step (one attribute store; handler threads read it GIL-atomically
        and a pre-attach scrape just 404s)."""
        self._engine = engine

    # -- endpoints --------------------------------------------------------

    def handle(self, method: str, req: BaseHTTPRequestHandler) -> None:
        if method != "GET":
            self.reply(req, 405, "metrics endpoint is read-only\n",
                       "text/plain")
            return
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self.reply(
                req, 200, self._registry.to_prometheus(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/metrics.json":
            self.reply(req, 200, self._registry.to_json(indent=2),
                       "application/json")
        elif path == "/healthz":
            code, body = flight_health(self._flight, self._stall_after)
            self.reply(req, code, json.dumps(body, indent=2),
                       "application/json")
        elif path == "/flight":
            self.reply(
                req, 200,
                json.dumps(self._flight.snapshot(), indent=2, default=str),
                "application/json",
            )
        elif path == "/requests":
            self.reply(
                req, 200,
                json.dumps(self._reqlog.snapshot(), indent=2),
                "application/json",
            )
        elif path.startswith("/request/"):
            self._request_detail(req, path[len("/request/"):])
        elif path == "/slots":
            if self._engine is None:
                self.reply(req, 404,
                           "no engine wired into this exporter\n",
                           "text/plain")
            else:
                self.reply(
                    req, 200,
                    json.dumps(self._engine.slots_snapshot(), indent=2),
                    "application/json",
                )
        elif path == "/":
            self.reply(
                req, 200,
                "tree_attention_tpu telemetry: /metrics /metrics.json "
                "/healthz /flight /requests /request/{uid} /slots\n",
                "text/plain",
            )
        else:
            self.reply(req, 404, f"no such endpoint: {path}\n",
                       "text/plain")

    def _request_detail(self, req: BaseHTTPRequestHandler,
                        tail: str) -> None:
        try:
            uid = int(tail)
        except ValueError:
            self.reply(req, 400, f"uid must be an integer, got {tail!r}\n",
                       "text/plain")
            return
        ledger = self._reqlog.get(uid)
        if ledger is None:
            self.reply(
                req, 404,
                f"no ledger for request {uid} (never seen, or evicted "
                f"from the recent ring)\n",
                "text/plain",
            )
            return
        self.reply(req, 200, json.dumps(ledger, indent=2),
                   "application/json")
