"""Live telemetry HTTP endpoint: /metrics, /metrics.json, /healthz, /flight.

PR 1's registry was built so "a future serving layer can mount it on
``/metrics`` unchanged" — this is that layer. A stdlib
:class:`~http.server.ThreadingHTTPServer` on a daemon thread (zero new
dependencies, dies with the process) serving:

- ``/metrics`` — Prometheus text exposition format, byte-identical to
  :meth:`MetricsRegistry.to_prometheus` at scrape time;
- ``/metrics.json`` — the registry snapshot (the same shape
  ``--metrics-out`` writes at exit, but live);
- ``/healthz`` — tick liveness from the flight recorder: 200 while the
  engine is recording ticks (or idle before any tick), 503 once the last
  tick is older than ``stall_after`` — a wedged tick loop fails the check
  even though the HTTP thread still answers (that asymmetry is the point);
- ``/flight`` — the flight recorder ring as JSON, the live post-mortem.

Scrapes hold the registry lock only for the duration of one snapshot —
the same cost an exit dump pays; the engine's disabled-path contract is
untouched (the server only *reads*).

Bind: localhost by default (telemetry is not an open service); pass
``host="0.0.0.0"`` explicitly to expose it. ``port=0`` lets the OS pick —
tests and parallel bench runs use that; :attr:`port` reports the bound
port after :meth:`start`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tree_attention_tpu.obs.flight import FLIGHT, FlightRecorder
from tree_attention_tpu.obs.metrics import REGISTRY, MetricsRegistry

DEFAULT_STALL_AFTER = 60.0


class MetricsHTTPServer:
    """Daemon-thread HTTP exporter over one registry + flight recorder."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        registry: MetricsRegistry = REGISTRY,
        flight: FlightRecorder = FLIGHT,
        stall_after: float = DEFAULT_STALL_AFTER,
    ):
        self._host = host
        self._want_port = port
        self._registry = registry
        self._flight = flight
        self._stall_after = stall_after
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr per scrape
                pass

            def do_GET(self):
                try:
                    server._handle(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-reply

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-http",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    @property
    def port(self) -> int:
        return 0 if self._httpd is None else self._httpd.server_address[1]

    @property
    def running(self) -> bool:
        return self._httpd is not None

    # -- endpoints --------------------------------------------------------

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._reply(
                req, 200, self._registry.to_prometheus(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/metrics.json":
            self._reply(req, 200, self._registry.to_json(indent=2),
                        "application/json")
        elif path == "/healthz":
            code, body = self._healthz()
            self._reply(req, code, json.dumps(body, indent=2),
                        "application/json")
        elif path == "/flight":
            self._reply(
                req, 200,
                json.dumps(self._flight.snapshot(), indent=2, default=str),
                "application/json",
            )
        elif path == "/":
            self._reply(
                req, 200,
                "tree_attention_tpu telemetry: /metrics /metrics.json "
                "/healthz /flight\n",
                "text/plain",
            )
        else:
            self._reply(req, 404, f"no such endpoint: {path}\n",
                        "text/plain")

    def _healthz(self):
        age = self._flight.last_tick_age()
        body = {
            "ticks_recorded": self._flight.ticks_recorded,
            "last_tick_age_s": None if age is None else round(age, 3),
            "stall_after_s": self._stall_after,
        }
        if age is None or self._flight.idle:
            # No tick yet, or the engine drained its run and said so
            # (mark_idle) — alive between runs, however long ago the last
            # tick was. Only a loop that stopped WITHOUT draining stalls.
            body["status"] = "idle"
            return 200, body
        if age <= self._stall_after:
            body["status"] = "ok"
            return 200, body
        body["status"] = "stalled"
        return 503, body

    @staticmethod
    def _reply(req: BaseHTTPRequestHandler, code: int, body: str,
               ctype: str) -> None:
        data = body.encode("utf-8")
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)
