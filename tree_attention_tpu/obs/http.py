"""Live telemetry HTTP endpoint: /metrics, /metrics.json, /healthz, /flight.

PR 1's registry was built so "a future serving layer can mount it on
``/metrics`` unchanged" — this is that layer. A stdlib
:class:`~http.server.ThreadingHTTPServer` on a daemon thread (zero new
dependencies, dies with the process) serving:

- ``/metrics`` — Prometheus text exposition format, byte-identical to
  :meth:`MetricsRegistry.to_prometheus` at scrape time;
- ``/metrics.json`` — the registry snapshot (the same shape
  ``--metrics-out`` writes at exit, but live);
- ``/healthz`` — tick liveness from the flight recorder: 200 while the
  engine is recording ticks (or idle before any tick), 503 once the last
  tick is older than ``stall_after`` — a wedged tick loop fails the check
  even though the HTTP thread still answers (that asymmetry is the point);
- ``/flight`` — the flight recorder ring as JSON, the live post-mortem.

Scrapes hold the registry lock only for the duration of one snapshot —
the same cost an exit dump pays; the engine's disabled-path contract is
untouched (the server only *reads*).

Server lifecycle (daemon thread, localhost bind, ``port=0`` OS-pick) is
the shared :class:`~tree_attention_tpu.utils.httpd.DaemonHTTPServer`
plumbing — the serving ingress rides the identical base.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler

from tree_attention_tpu.obs.flight import FLIGHT, FlightRecorder
from tree_attention_tpu.obs.metrics import REGISTRY, MetricsRegistry
from tree_attention_tpu.utils.httpd import DaemonHTTPServer

DEFAULT_STALL_AFTER = 60.0


class MetricsHTTPServer(DaemonHTTPServer):
    """Daemon-thread HTTP exporter over one registry + flight recorder."""

    thread_name = "obs-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        registry: MetricsRegistry = REGISTRY,
        flight: FlightRecorder = FLIGHT,
        stall_after: float = DEFAULT_STALL_AFTER,
    ):
        super().__init__(port, host)
        self._registry = registry
        self._flight = flight
        self._stall_after = stall_after

    # -- endpoints --------------------------------------------------------

    def handle(self, method: str, req: BaseHTTPRequestHandler) -> None:
        if method != "GET":
            self.reply(req, 405, "metrics endpoint is read-only\n",
                       "text/plain")
            return
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self.reply(
                req, 200, self._registry.to_prometheus(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/metrics.json":
            self.reply(req, 200, self._registry.to_json(indent=2),
                       "application/json")
        elif path == "/healthz":
            code, body = self._healthz()
            self.reply(req, code, json.dumps(body, indent=2),
                       "application/json")
        elif path == "/flight":
            self.reply(
                req, 200,
                json.dumps(self._flight.snapshot(), indent=2, default=str),
                "application/json",
            )
        elif path == "/":
            self.reply(
                req, 200,
                "tree_attention_tpu telemetry: /metrics /metrics.json "
                "/healthz /flight\n",
                "text/plain",
            )
        else:
            self.reply(req, 404, f"no such endpoint: {path}\n",
                       "text/plain")

    def _healthz(self):
        age = self._flight.last_tick_age()
        body = {
            "ticks_recorded": self._flight.ticks_recorded,
            "last_tick_age_s": None if age is None else round(age, 3),
            "stall_after_s": self._stall_after,
        }
        if age is None or self._flight.idle:
            # No tick yet, or the engine drained its run and said so
            # (mark_idle) — alive between runs, however long ago the last
            # tick was. Only a loop that stopped WITHOUT draining stalls.
            body["status"] = "idle"
            return 200, body
        if age <= self._stall_after:
            body["status"] = "ok"
            return 200, body
        body["status"] = "stalled"
        return 503, body
