"""Telemetry: the process-wide metrics registry and host-side span tracer.

Two complementary instruments, both off (and free) by default:

- :mod:`~tree_attention_tpu.obs.metrics` — thread-safe counters / gauges /
  fixed-bucket histograms with labels; exportable as JSON
  (``--metrics-out``) and Prometheus text format.
- :mod:`~tree_attention_tpu.obs.tracing` — span tracer emitting
  Chrome-trace-format JSONL (``--trace-events``), loadable in Perfetto
  alongside ``jax.profiler`` device traces; ``pid`` is the JAX process
  index so multi-host captures merge cleanly.

The serving observability plane builds on both: the tick flight recorder
(:mod:`~tree_attention_tpu.obs.flight`, ``--flight-out``), the
sliding-window SLO monitor (:mod:`~tree_attention_tpu.obs.slo`), and the
live HTTP exporter (:mod:`~tree_attention_tpu.obs.http`,
``--metrics-port`` — imported lazily; mounting ``/metrics`` must not tax
every library import). :func:`install_crash_handlers` makes all sinks
crash-safe (atexit + SIGTERM flush, SIGUSR1 live dump).

Lifecycle: the CLI (or any embedder) calls :func:`configure` once at
startup and :func:`shutdown` at exit; instrumentation sites declare their
metrics at import via :func:`counter` / :func:`gauge` / :func:`histogram`
and record unconditionally — the disabled path is a single flag check.

Environment fallbacks ``TA_METRICS_OUT`` / ``TA_TRACE_EVENTS`` let
subprocesses a run spawns (``--launch`` ranks) inherit telemetry without
plumbing flags; explicit arguments win, and spawners whose children have
no rank contract strip the vars instead (``bench.py``'s comparator
subprocesses — an unsuffixed child would clobber the parent's sinks).
Multi-process runs rank-suffix BOTH sink paths (each process owns its
files — the tracer truncates on open, so ranks must never share a path);
trace events additionally carry the rank as ``pid`` so the per-rank files
merge into one Perfetto timeline.
"""

from __future__ import annotations

import atexit
import os
from typing import Any, Dict, Optional

from tree_attention_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    gauge,
    histogram,
    percentile,
)
from tree_attention_tpu.obs.tracing import (  # noqa: F401
    SpanTracer,
    TRACEPARENT_HEADER,
    TRACER,
    flow,
    flow_id,
    instant,
    make_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    span,
    traced,
)
from tree_attention_tpu.obs.flight import (  # noqa: F401
    FLIGHT,
    FlightRecorder,
)
from tree_attention_tpu.obs.reqlog import (  # noqa: F401
    REQLOG,
    ReqLog,
    RequestLedger,
    aggregate_ledgers,
)
from tree_attention_tpu.obs.slo import SLOMonitor  # noqa: F401

_STATE: Dict[str, Optional[str]] = {"metrics_out": None}


def enabled() -> bool:
    """True when the metrics registry records (the tracer has its own
    ``TRACER.active`` — either instrument can run alone)."""
    return REGISTRY.enabled


def enable() -> None:
    REGISTRY.enable()


def disable() -> None:
    REGISTRY.disable()


def _rank_suffixed(path: str) -> str:
    """Each process of a multi-process run owns its own metrics file —
    same convention as the CLI's rank-suffixed ``--log-file``. Detects
    both the local launcher's env contract (``TA_COORDINATOR``) and an
    already-initialized multi-host JAX runtime (metadata-server
    auto-detect), so N hosts on a shared filesystem never clobber one
    path; callers should configure *after* distributed init (the CLI
    does)."""
    from tree_attention_tpu.utils.logging import _process_count, _process_index

    if os.environ.get("TA_COORDINATOR") or _process_count() > 1:
        return f"{path}.p{_process_index()}"
    return path


def configure(
    metrics_out: Optional[str] = None,
    trace_events: Optional[str] = None,
    flight_out: Optional[str] = None,
) -> None:
    """Arm telemetry for this process.

    ``metrics_out``: path the exit snapshot (JSON) is written to by
    :func:`shutdown`; enables the registry. ``trace_events``: Chrome-trace
    JSONL sink path; starts the span tracer. ``flight_out``: arms the
    tick flight recorder with a crash-dump sink (written by
    :func:`shutdown`, on engine error, and by the signal handlers).
    ``None`` falls back to ``TA_METRICS_OUT`` / ``TA_TRACE_EVENTS`` /
    ``TA_FLIGHT_OUT`` so child processes inherit the parent's telemetry
    choice.
    """
    metrics_out = metrics_out or os.environ.get("TA_METRICS_OUT")
    trace_events = trace_events or os.environ.get("TA_TRACE_EVENTS")
    flight_out = flight_out or os.environ.get("TA_FLIGHT_OUT")
    if metrics_out:
        _STATE["metrics_out"] = _rank_suffixed(metrics_out)
        REGISTRY.enable()
    if trace_events:
        TRACER.start(_rank_suffixed(trace_events))
        # Spans without counters are half a story (and vice versa): one
        # flag arms both; --metrics-out alone still skips the JSON dump.
        REGISTRY.enable()
    if flight_out:
        FLIGHT.arm(_rank_suffixed(flight_out))
    if metrics_out or trace_events:
        # The request ledger rides whichever instrument is on: its
        # /requests view backs the metrics plane and its finish instant
        # lands in the trace; it has no sink file of its own.
        REQLOG.arm()


def shutdown() -> Dict[str, Any]:
    """Flush sinks: write the metrics snapshot (if configured), dump the
    flight recorder (if armed with a sink), close the tracer, and DISARM —
    a later run in the same process records nothing (and rewrites no
    earlier run's file) unless it calls :func:`configure` again. Metric
    values persist across configure cycles (process-lifetime totals); only
    the sinks and the enabled flag reset. Idempotent. Returns
    ``{"metrics_out": ..., "trace_events": ..., "flight_out": ...}`` — the
    sinks THIS run actually wrote — for the caller's exit log line."""
    out: Dict[str, Any] = {
        "metrics_out": None,
        "trace_events": TRACER.path if TRACER.active else None,
        "flight_out": None,
    }
    path = _STATE["metrics_out"]
    if path and REGISTRY.enabled:
        try:
            REGISTRY.write_json(path)
            out["metrics_out"] = path
        except OSError:
            pass  # never let observability fail the run at exit
    out["flight_out"] = FLIGHT.dump_if_armed("shutdown")
    _STATE["metrics_out"] = None
    REGISTRY.disable()
    TRACER.close()
    FLIGHT.disarm()
    REQLOG.disarm()
    return out


def flush() -> Dict[str, Any]:
    """Crash-time flush: write every armed sink WITHOUT disarming — the
    run may continue (SIGUSR1) or die an instant later (SIGTERM/atexit);
    either way the telemetry captured so far is on disk. Safe to call
    repeatedly; never raises."""
    out: Dict[str, Any] = {
        "metrics_out": None, "trace_events": None, "flight_out": None,
    }
    path = _STATE["metrics_out"]
    if path and REGISTRY.enabled:
        try:
            REGISTRY.write_json(path)
            out["metrics_out"] = path
        except OSError:
            pass
    if TRACER.active:
        TRACER.flush()
        out["trace_events"] = TRACER.path
    out["flight_out"] = FLIGHT.dump_if_armed("flush")
    return out


_HANDLERS: Dict[str, Any] = {"installed": False}


def install_crash_handlers() -> bool:
    """Make telemetry crash-safe: an interrupted run still flushes.

    Registers (idempotently, main thread only — signal handlers cannot be
    installed elsewhere; returns False in that case):

    - ``atexit`` — :func:`flush` as a backstop for exits that skip the
      caller's ``finally`` (``os._exit`` excepted; nothing catches that);
    - ``SIGTERM`` — flush every armed sink, restore the previous handler,
      and re-raise the signal so the process still dies with the standard
      143 (a supervisor's kill must stay a kill);
    - ``SIGUSR1`` — dump the flight recorder + flush and KEEP RUNNING: the
      live "what is this server doing" poke for a wedged-looking run.
    """
    import signal

    if _HANDLERS["installed"]:
        return True
    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            flush()
            signal.signal(
                signal.SIGTERM,
                prev_term if prev_term is not None else signal.SIG_DFL,
            )
            os.kill(os.getpid(), signum)

        def _on_usr1(signum, frame):
            flush()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGUSR1, _on_usr1)
    except ValueError:  # not the main thread
        return False
    atexit.register(flush)
    _HANDLERS["installed"] = True
    return True
