"""Telemetry: the process-wide metrics registry and host-side span tracer.

Two complementary instruments, both off (and free) by default:

- :mod:`~tree_attention_tpu.obs.metrics` — thread-safe counters / gauges /
  fixed-bucket histograms with labels; exportable as JSON
  (``--metrics-out``) and Prometheus text format.
- :mod:`~tree_attention_tpu.obs.tracing` — span tracer emitting
  Chrome-trace-format JSONL (``--trace-events``), loadable in Perfetto
  alongside ``jax.profiler`` device traces; ``pid`` is the JAX process
  index so multi-host captures merge cleanly.

Lifecycle: the CLI (or any embedder) calls :func:`configure` once at
startup and :func:`shutdown` at exit; instrumentation sites declare their
metrics at import via :func:`counter` / :func:`gauge` / :func:`histogram`
and record unconditionally — the disabled path is a single flag check.

Environment fallbacks ``TA_METRICS_OUT`` / ``TA_TRACE_EVENTS`` let
subprocesses a run spawns (``--launch`` ranks) inherit telemetry without
plumbing flags; explicit arguments win, and spawners whose children have
no rank contract strip the vars instead (``bench.py``'s comparator
subprocesses — an unsuffixed child would clobber the parent's sinks).
Multi-process runs rank-suffix BOTH sink paths (each process owns its
files — the tracer truncates on open, so ranks must never share a path);
trace events additionally carry the rank as ``pid`` so the per-rank files
merge into one Perfetto timeline.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from tree_attention_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    gauge,
    histogram,
)
from tree_attention_tpu.obs.tracing import (  # noqa: F401
    SpanTracer,
    TRACER,
    instant,
    span,
    traced,
)

_STATE: Dict[str, Optional[str]] = {"metrics_out": None}


def enabled() -> bool:
    """True when the metrics registry records (the tracer has its own
    ``TRACER.active`` — either instrument can run alone)."""
    return REGISTRY.enabled


def enable() -> None:
    REGISTRY.enable()


def disable() -> None:
    REGISTRY.disable()


def _rank_suffixed(path: str) -> str:
    """Each process of a multi-process run owns its own metrics file —
    same convention as the CLI's rank-suffixed ``--log-file``. Detects
    both the local launcher's env contract (``TA_COORDINATOR``) and an
    already-initialized multi-host JAX runtime (metadata-server
    auto-detect), so N hosts on a shared filesystem never clobber one
    path; callers should configure *after* distributed init (the CLI
    does)."""
    from tree_attention_tpu.utils.logging import _process_count, _process_index

    if os.environ.get("TA_COORDINATOR") or _process_count() > 1:
        return f"{path}.p{_process_index()}"
    return path


def configure(
    metrics_out: Optional[str] = None,
    trace_events: Optional[str] = None,
) -> None:
    """Arm telemetry for this process.

    ``metrics_out``: path the exit snapshot (JSON) is written to by
    :func:`shutdown`; enables the registry. ``trace_events``: Chrome-trace
    JSONL sink path; starts the span tracer. ``None`` falls back to
    ``TA_METRICS_OUT`` / ``TA_TRACE_EVENTS`` so child processes inherit
    the parent's telemetry choice.
    """
    metrics_out = metrics_out or os.environ.get("TA_METRICS_OUT")
    trace_events = trace_events or os.environ.get("TA_TRACE_EVENTS")
    if metrics_out:
        _STATE["metrics_out"] = _rank_suffixed(metrics_out)
        REGISTRY.enable()
    if trace_events:
        TRACER.start(_rank_suffixed(trace_events))
        # Spans without counters are half a story (and vice versa): one
        # flag arms both; --metrics-out alone still skips the JSON dump.
        REGISTRY.enable()


def shutdown() -> Dict[str, Any]:
    """Flush sinks: write the metrics snapshot (if configured), close the
    tracer, and DISARM — a later run in the same process records nothing
    (and rewrites no earlier run's file) unless it calls :func:`configure`
    again. Metric values persist across configure cycles (process-lifetime
    totals); only the sinks and the enabled flag reset. Idempotent.
    Returns ``{"metrics_out": path-or-None, "trace_events": path-or-None}``
    — the sinks THIS run actually wrote — for the caller's exit log line."""
    out: Dict[str, Any] = {
        "metrics_out": None,
        "trace_events": TRACER.path if TRACER.active else None,
    }
    path = _STATE["metrics_out"]
    if path and REGISTRY.enabled:
        try:
            REGISTRY.write_json(path)
            out["metrics_out"] = path
        except OSError:
            pass  # never let observability fail the run at exit
    _STATE["metrics_out"] = None
    REGISTRY.disable()
    TRACER.close()
    return out
