"""Host-side span tracer: Chrome-trace-format JSONL, one event per line.

``jax.profiler`` (``utils/profiling.trace``) captures what the *device* did;
nothing captured where the *host* spent a run's wall clock — compile vs
launch vs timing cycles vs checkpoint IO. This tracer fills that gap with
explicit spans (context manager or decorator) emitted as Chrome trace
events, loadable in Perfetto / ``chrome://tracing`` alongside the device
profile:

- Each line of the output file is one complete JSON object (``json.loads``
  per line succeeds — the machine-checkable contract). Perfetto's JSON
  tokenizer accepts concatenated objects without an enclosing array, and a
  consumer that insists on strict Chrome JSON can wrap the lines with
  ``[`` … ``]`` mechanically.
- ``pid`` is the JAX process index (not the OS pid), so traces captured on
  different hosts of a multi-process run merge into one timeline with one
  row group per rank. ``tid`` is a small per-thread ordinal; process/thread
  metadata events name both.
- Complete events (``ph: "X"``) are written at span *close* with
  microsecond ``ts``/``dur`` from the monotonic clock; instants
  (``ph: "i"``) record point occurrences (guard verdicts, watchdog stalls,
  rank exits).

Disabled (no sink installed — the default) is free: :func:`span` returns a
shared no-op context manager after one attribute check, and
:func:`instant` returns immediately. Same contract as the metrics
registry's disabled path.
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


# -- W3C-traceparent-style request context -----------------------------------
#
# One request = one trace_id, minted at the FIRST ingress that sees it
# (router-fronted fleets: the router's relay forwards the header and the
# replica ingress ADOPTS instead of minting). Each process that handles the
# request stamps its own span_id. The wire format is the W3C traceparent
# header, ``00-<32 hex trace_id>-<16 hex span_id>-01`` — close enough that
# off-the-shelf middleboxes pass it through untouched.

TRACEPARENT_HEADER = "traceparent"


def new_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random span id, 16 lowercase hex chars."""
    return os.urandom(8).hex()


def make_traceparent(trace_id: str, span_id: str) -> str:
    """Serialize to the W3C header value (version 00, sampled flag set)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """Parse a traceparent header value → ``(trace_id, span_id)``.

    Returns ``None`` for anything malformed (wrong field count, wrong
    lengths, non-hex, all-zero ids) — the caller mints a fresh context
    instead of propagating garbage.
    """
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def flow_id(trace_id: str) -> int:
    """Chrome-trace flow ``id`` for a trace: the low 53 bits of the
    trace_id (kept under 2**53 so JSON consumers that parse numbers as
    doubles — Perfetto's legacy JSON importer among them — round-trip it
    exactly)."""
    return int(trace_id[-14:], 16) & ((1 << 53) - 1)


class _NoopSpan:
    """Singleton no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args: Any) -> None:
        """No-op twin of :meth:`_Span.set`."""


_NOOP_SPAN = _NoopSpan()


class _Span:
    """One open span; emits a complete ("X") event when it closes."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = time.monotonic_ns()

    def set(self, **args: Any) -> None:
        """Attach/extend args mid-span (recorded when the span closes)."""
        if self.args is None:
            self.args = {}
        self.args.update(args)

    def __enter__(self) -> "_Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        t1 = time.monotonic_ns()
        self._tracer._emit_complete(
            self.name, self.cat, self._t0 // 1000, (t1 - self._t0) // 1000,
            self.args,
        )
        return False


class SpanTracer:
    """Writes Chrome trace events to a JSONL sink; inactive until started.

    Spans may nest freely (Chrome's flattener reconstructs the stack from
    enclosing ``ts``/``dur`` per tid) and may close out of start order
    across threads — each event is self-contained.
    """

    def __init__(self):
        # Reentrant: the crash handlers (obs.flush) run on the main thread
        # and may interrupt an _emit holding this lock — a plain Lock
        # would deadlock the flush-then-die path instead of flushing.
        self._lock = threading.RLock()
        self._file = None
        self._path: Optional[str] = None
        self._pid = 0
        self._tids: Dict[int, int] = {}
        self.active = False

    # -- lifecycle --------------------------------------------------------

    def start(self, path: str) -> None:
        """Open (truncate) the sink and emit process metadata."""
        from tree_attention_tpu.utils.logging import _process_index

        with self._lock:
            if self._file is not None:
                self._file.close()
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._file = open(path, "w")
            self._path = path
            self._pid = _process_index()
            self._tids = {}
            self.active = True
            self._write_locked({
                "name": "process_name", "ph": "M", "pid": self._pid,
                "tid": 0, "args": {"name": f"host rank {self._pid}"},
            })
        atexit.register(self.close)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            self.active = False

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()

    @property
    def path(self) -> Optional[str]:
        return self._path

    # -- recording --------------------------------------------------------

    def span(self, name: str, cat: str = "host",
             args: Optional[Dict[str, Any]] = None):
        """Context manager timing a host-side phase.

        Pass structured detail via ``args`` (one dict, not kwargs — the
        disabled path must not build anything). Spans around code that JAX
        *traces* measure tracing/compile time, not execution; use
        ``cat="trace"`` there so the timeline says so.
        """
        if not self.active:
            return _NOOP_SPAN
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "host",
                args: Optional[Dict[str, Any]] = None) -> None:
        """Point-in-time event (guard verdict, stall, rank exit)."""
        if not self.active:
            return
        self._emit({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": time.monotonic_ns() // 1000,
            "pid": self._pid, "tid": self._tid(),
            **({"args": args} if args else {}),
        })

    def counter_event(self, name: str, values: Dict[str, float]) -> None:
        """Chrome counter track ("C") — a value series over the timeline."""
        if not self.active:
            return
        self._emit({
            "name": name, "ph": "C", "ts": time.monotonic_ns() // 1000,
            "pid": self._pid, "tid": self._tid(), "args": values,
        })

    def flow(self, phase: str, fid: int, name: str = "request",
             cat: str = "serving") -> None:
        """Chrome-trace flow event binding cross-process arrows.

        ``phase`` is ``"s"`` (start), ``"t"`` (step), or ``"f"`` (finish);
        ``fid`` is the shared flow id (:func:`flow_id` of the trace_id).
        Flow points bind to whichever slice encloses their ``ts`` on this
        pid/tid — emit them INSIDE the span that should anchor the arrow.
        Perfetto then draws one connected arrow chain across every process
        file merged into the load (``tools/trace_merge.py``).
        """
        if not self.active:
            return
        ev: Dict[str, Any] = {
            "name": name, "cat": cat, "ph": phase, "id": fid,
            "ts": time.monotonic_ns() // 1000,
            "pid": self._pid, "tid": self._tid(),
        }
        if phase == "f":
            ev["bp"] = "e"  # bind the finish to the enclosing slice
        self._emit(ev)

    # -- internals --------------------------------------------------------

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
                t = threading.current_thread()
                self._write_locked({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid, "args": {"name": t.name},
                })
        return tid

    def _emit_complete(self, name, cat, ts_us, dur_us, args) -> None:
        if not self.active:
            return  # sink closed while the span was open
        self._emit({
            "name": name, "cat": cat, "ph": "X",
            "ts": ts_us, "dur": dur_us,
            "pid": self._pid, "tid": self._tid(),
            **({"args": args} if args else {}),
        })

    def _emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._write_locked(event)

    def _write_locked(self, event: Dict[str, Any]) -> None:
        if self._file is None:
            return
        try:
            self._file.write(json.dumps(event, default=str) + "\n")
        except (OSError, ValueError):
            pass  # never let observability kill the workload


#: The process-wide tracer every instrumentation site uses.
TRACER = SpanTracer()


def span(name: str, cat: str = "host",
         args: Optional[Dict[str, Any]] = None):
    """Module-level shorthand for ``TRACER.span`` (the common call site)."""
    if not TRACER.active:
        return _NOOP_SPAN
    return _Span(TRACER, name, cat, args)


def instant(name: str, cat: str = "host",
            args: Optional[Dict[str, Any]] = None) -> None:
    TRACER.instant(name, cat, args)


def flow(phase: str, fid: int, name: str = "request",
         cat: str = "serving") -> None:
    """Module-level shorthand for ``TRACER.flow``."""
    TRACER.flow(phase, fid, name, cat)


def traced(name: Optional[str] = None, cat: str = "host") -> Callable:
    """Decorator form: ``@traced()`` wraps the call in a span.

    The wrapper costs one flag check when tracing is off — cheap enough for
    per-call host functions, still not for per-element inner loops.
    """

    def deco(fn: Callable) -> Callable:
        span_name = name or f"{fn.__module__.split('.')[-1]}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not TRACER.active:
                return fn(*a, **kw)
            with _Span(TRACER, span_name, cat, None):
                return fn(*a, **kw)

        return wrapper

    return deco
