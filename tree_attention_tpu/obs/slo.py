"""Sliding-window SLO monitor: live TTFT / TBT / queue-wait percentiles.

End-of-run percentiles (``ServeReport``) answer "how did the run go";
a serving process needs "how is it going NOW" — windowed latencies a
scraper can watch move, and **goodput**: the fraction of recently retired
requests that met a configurable TTFT+TBT SLO, the Sarathi-style headline
(arXiv:2403.02310 §6 evaluates exactly this). Chunked admission exists to
protect TTFT and TBT under load; this monitor is where that protection
becomes continuously observable instead of bench-reported.

Mechanics: three bounded sample windows (TTFT, TBT, queue wait — a deque
of the last ``window`` observations each, O(1) per observation) plus a
window of per-request SLO verdicts. A request meets the SLO iff its TTFT
``<= ttft_slo`` AND its worst inter-token gap ``<= tbt_slo`` (max, not
p95 — one visible stall breaks the experience the SLO describes).
Percentiles are exact nearest-rank over the window
(:func:`~tree_attention_tpu.obs.metrics.percentile` — the shared
definition). :meth:`maybe_export` re-publishes the gauges at most once per
``export_every`` seconds, so the per-tick cost stays one time check; the
gauges appear on ``/metrics`` as ``serving_slo_*{q=...}`` and
``serving_goodput_ratio``.

:meth:`snapshot` additionally reports run-lifetime quantiles interpolated
from the cumulative ``serving_ttft_seconds`` / ``serving_tbt_seconds``
histograms (:meth:`Histogram.quantile
<tree_attention_tpu.obs.metrics.Histogram.quantile>`) when the registry is
recording — window vs lifetime disagreement is itself a signal (the run
degraded or recovered).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from tree_attention_tpu.obs import metrics as _m
from tree_attention_tpu.obs.metrics import percentile

DEFAULT_WINDOW = 1024
_QS = ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))

# The live-window gauges (one labeled family per latency, quantile as a
# label so a scraper gets the whole distribution in one series).
_SLO_TTFT = _m.gauge(
    "serving_slo_ttft_seconds",
    "sliding-window TTFT quantiles over recent requests", labels=("q",),
)
_SLO_TBT = _m.gauge(
    "serving_slo_tbt_seconds",
    "sliding-window inter-token-latency quantiles over recent tokens",
    labels=("q",),
)
_SLO_QWAIT = _m.gauge(
    "serving_slo_queue_wait_seconds",
    "sliding-window queue-wait quantiles over recent admissions",
    labels=("q",),
)
_GOODPUT = _m.gauge(
    "serving_goodput_ratio",
    "fraction of recently retired requests meeting the TTFT+TBT SLO",
)
_SLO_WINDOW_REQS = _m.gauge(
    "serving_slo_window_requests",
    "retired requests currently inside the goodput window",
)


class SLOMonitor:
    """Windowed latency percentiles + goodput against a TTFT/TBT SLO."""

    def __init__(
        self,
        *,
        ttft_slo: float = 1.0,
        tbt_slo: float = 0.2,
        window: int = DEFAULT_WINDOW,
        export_every: float = 1.0,
    ):
        if ttft_slo <= 0 or tbt_slo <= 0:
            raise ValueError(
                f"SLO thresholds must be > 0, got ttft={ttft_slo} "
                f"tbt={tbt_slo}"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.ttft_slo = float(ttft_slo)
        self.tbt_slo = float(tbt_slo)
        self.window = int(window)
        self.export_every = float(export_every)
        self._lock = threading.Lock()
        self._ttft: deque = deque(maxlen=window)
        self._tbt: deque = deque(maxlen=window)
        self._qwait: deque = deque(maxlen=window)
        self._met: deque = deque(maxlen=window)
        self._retired = 0
        self._last_export = 0.0

    # -- feeding (engine-side, O(1) each) ---------------------------------

    def reset(self) -> None:
        """Drop every window and verdict (SLO targets stay). For callers
        reusing one engine across distinct runs — a bench's warmup must
        not leave its compile-stalled requests in the measured runs'
        goodput window."""
        with self._lock:
            self._ttft.clear()
            self._tbt.clear()
            self._qwait.clear()
            self._met.clear()
            self._retired = 0

    def observe_ttft(self, v: float) -> None:
        with self._lock:
            self._ttft.append(v)

    def observe_tbt(self, v: float) -> None:
        with self._lock:
            self._tbt.append(v)

    def observe_queue_wait(self, v: float) -> None:
        with self._lock:
            self._qwait.append(v)

    def observe_request(self, ttft_s: float, max_tbt_s: float) -> bool:
        """One retired request's verdict against the SLO; returns it."""
        met = ttft_s <= self.ttft_slo and max_tbt_s <= self.tbt_slo
        with self._lock:
            self._met.append(met)
            self._retired += 1
        return met

    def observe_miss(self) -> None:
        """One retired request that categorically missed the SLO without
        producing latency samples — deadline-expired, shed under load,
        or errored (the ISSUE 10 outcome vocabulary). Counted as a
        goodput failure; its (nonexistent) latencies stay out of the
        percentile windows."""
        with self._lock:
            self._met.append(False)
            self._retired += 1

    # -- reading ----------------------------------------------------------

    def goodput(self) -> float:
        """Fraction of the goodput window meeting the SLO (1.0 when no
        request has retired yet — an idle server is not failing its SLO)."""
        with self._lock:
            if not self._met:
                return 1.0
            return sum(self._met) / len(self._met)

    def _window_quantiles(self) -> Dict[str, float]:
        with self._lock:
            ttft = sorted(self._ttft)
            tbt = sorted(self._tbt)
            qwait = sorted(self._qwait)
        out: Dict[str, float] = {}
        for p, tag in _QS:
            out[f"ttft_{tag}_s"] = percentile(ttft, p)
            out[f"tbt_{tag}_s"] = percentile(tbt, p)
            out[f"queue_wait_{tag}_s"] = percentile(qwait, p)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The JSON shape ``ServeReport`` and ``--mode serve`` surface."""
        out: Dict[str, Any] = {
            "slo": {"ttft_s": self.ttft_slo, "tbt_s": self.tbt_slo},
            "goodput": round(self.goodput(), 4),
            "window": self.window,
            "requests_in_window": len(self._met),
            "requests_retired": self._retired,
        }
        out.update({
            k: round(v, 6) for k, v in self._window_quantiles().items()
        })
        if _m.REGISTRY.enabled:
            # Run-lifetime quantiles via bucket interpolation — the
            # Histogram.quantile reuse; drift from the window values above
            # means the run's tail moved.
            for name, key in (("serving_ttft_seconds", "ttft"),
                              ("serving_tbt_seconds", "tbt")):
                h = _m.REGISTRY.get(name)
                if h is not None and isinstance(h, _m.Histogram):
                    for p, tag in _QS:
                        out[f"{key}_lifetime_{tag}_s"] = round(
                            h.quantile(p), 6
                        )
        return out

    # -- exporting --------------------------------------------------------

    def export_gauges(self) -> None:
        """Publish the window quantiles + goodput to the registry gauges
        (no-op while the registry is disabled)."""
        if not _m.REGISTRY.enabled:
            return
        q = self._window_quantiles()
        for _, tag in _QS:
            _SLO_TTFT.labels(q=tag).set(q[f"ttft_{tag}_s"])
            _SLO_TBT.labels(q=tag).set(q[f"tbt_{tag}_s"])
            _SLO_QWAIT.labels(q=tag).set(q[f"queue_wait_{tag}_s"])
        _GOODPUT.set(self.goodput())
        _SLO_WINDOW_REQS.set(len(self._met))

    def maybe_export(self, now: Optional[float] = None) -> None:
        """Rate-limited :meth:`export_gauges` — the per-tick call site.
        One time comparison per tick; the sort only runs when a scrape
        could actually see fresh values."""
        if not _m.REGISTRY.enabled:
            return
        if now is None:
            now = time.monotonic()
        with self._lock:  # claim the export slot before releasing: two
            # ticks racing here must not both pay the window sort
            if now - self._last_export < self.export_every:
                return
            self._last_export = now
        self.export_gauges()
