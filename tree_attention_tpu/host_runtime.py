"""ctypes bindings for the native host runtime (``tree_attention_tpu/native/treeattn_host.cc``).

The reference gets its host-side native capability for free from libtorch:
ATen's Philox RNG (``/root/reference/model.py:50``) and multiprocessing's
fork/exec layer (``model.py:165``). This module binds the framework's own C++
equivalents — counter-based RNG fills, a prefetching batch pipeline, and a
local process launcher — compiling the shared library on first use (g++ is
part of the toolchain; there is no pybind11 in this image, hence ctypes).

Everything degrades gracefully: if the compiler or library is unavailable,
:func:`philox_tokens` / :class:`HostDataPipeline` fall back to NumPy's own
Philox implementation (same counter-based construction, different stream),
and :func:`launch_local` falls back to ``subprocess``. The contract is
"deterministic in (seed, index) within a backend", not cross-backend
bit-equality — synthetic data needs no more.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import signal
import subprocess
import tempfile
import time
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tree_attention_tpu import obs
from tree_attention_tpu.utils.logging import get_logger

log = get_logger("host_runtime")

# Launcher/watchdog observability (all host-side, execution-true — nothing
# here is ever traced by JAX). Exit statuses are classified with the same
# conventions the supervisor reports: 124 deadline, 125 heartbeat stall,
# 128+sig crash-by-signal.
_HEARTBEATS = obs.counter(
    "heartbeat_ticks_total",
    "host-visible progress marks (one per train step / fenced timing "
    "iteration)",
)
_GANG_LAUNCHES = obs.counter(
    "gang_launches_total", "launch_local invocations"
)
_GANG_ATTEMPTS = obs.counter(
    "gang_attempts_total",
    "gang launch attempts, including elastic relaunches",
)
_RANK_EXITS = obs.counter(
    "rank_exits_total",
    "child rank exits by outcome (ok / crash / deadline / stall)",
    labels=("outcome",),
)
_WATCHDOG_STALLS = obs.counter(
    "watchdog_stalls_total",
    "heartbeat watchdog firings (whole-gang kills, status 125)",
)


def _rank_exit_outcome(status: int) -> str:
    if status == 0:
        return "ok"
    if status == 124:
        return "deadline"
    if status == 125:
        return "stall"
    return "crash"


def _account_gang_result(statuses: Sequence[int]) -> None:
    if obs.REGISTRY.enabled:
        for s in statuses:
            _RANK_EXITS.labels(outcome=_rank_exit_outcome(s)).inc()
        if any(s == 125 for s in statuses):
            _WATCHDOG_STALLS.inc()
    if obs.TRACER.active and any(s == 125 for s in statuses):
        # Own guard: a tracer-only run used to lose the stall instant to
        # the registry early-return above.
        obs.instant("watchdog_stall", cat="launcher",
                    args={"statuses": list(statuses)})

# The native sources ship inside the package (``tree_attention_tpu/native``
# is package data, pyproject ``[tool.setuptools.package-data]``) so an
# installed wheel can build the runtime on first use, same as a source
# checkout. When the install location is read-only (system site-packages),
# the build lands in ``~/.cache/tree-attention-tpu`` instead.
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_SRC_PATH = os.path.join(_NATIVE_DIR, "treeattn_host.cc")


def _build_dir() -> str:
    if os.access(_NATIVE_DIR, os.W_OK):
        return os.path.join(_NATIVE_DIR, "build")
    # Read-only install: build into the user cache, keyed by the SOURCE
    # content hash — two venvs with different package versions must not
    # share one .so (the mtime staleness check cannot catch a newer .so
    # built from a different install's source, and ctypes would bind old
    # prototypes to a mismatched library).
    import hashlib

    try:
        with open(_SRC_PATH, "rb") as f:
            key = hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        key = "unknown"
    return os.path.join(
        os.path.expanduser("~"), ".cache", "tree-attention-tpu", key
    )


def _so_path() -> str:
    return os.path.join(_build_dir(), "libtreeattn_host.so")


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _compile() -> bool:
    try:
        proc = subprocess.run(
            ["make", "-C", _NATIVE_DIR, "BUILD=" + _build_dir()],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0:
            log.warning("native build failed:\n%s", proc.stderr[-2000:])
            return False
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable: %s", e)
        return False


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        so = _so_path()
        stale = not os.path.exists(so) or (
            os.path.exists(_SRC_PATH)
            and os.path.getmtime(_SRC_PATH) > os.path.getmtime(so)
        )
        if stale and not _compile():
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("native library load failed: %s", e)
            return None
        lib.ta_fill_u32.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.ta_fill_normal_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.ta_fill_tokens_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_size_t, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.ta_pipeline_create.restype = ctypes.c_void_p
        lib.ta_pipeline_create.argtypes = [
            ctypes.c_size_t, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.ta_pipeline_next.restype = ctypes.c_int64
        lib.ta_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ta_pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.ta_launch_processes.restype = ctypes.c_int
        lib.ta_launch_processes.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        if not hasattr(lib, "ta_launch_processes_supervised"):
            # A prebuilt .so from before this symbol existed whose mtime
            # defeated the staleness check: treat the native runtime as
            # unavailable rather than AttributeError-ing at call time.
            log.warning("stale libtreeattn_host.so (missing supervised "
                        "launcher); using the pure-python fallbacks")
            return None
        lib.ta_launch_processes_supervised.restype = ctypes.c_int
        lib.ta_launch_processes_supervised.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        if hasattr(lib, "ta_launch_processes_watched"):
            lib.ta_launch_processes_watched.restype = ctypes.c_int
            lib.ta_launch_processes_watched.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
            ]
        if hasattr(lib, "ta_launch_processes_elastic"):
            lib.ta_launch_processes_elastic.restype = ctypes.c_int
            lib.ta_launch_processes_elastic.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_int,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
            ]
        if hasattr(lib, "ta_corpus_open"):
            lib.ta_corpus_open.restype = ctypes.c_void_p
            lib.ta_corpus_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.ta_corpus_len.restype = ctypes.c_int64
            lib.ta_corpus_len.argtypes = [ctypes.c_void_p]
            lib.ta_corpus_close.argtypes = [ctypes.c_void_p]
            lib.ta_corpus_fill_batch.restype = ctypes.c_int
            lib.ta_corpus_fill_batch.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
                ctypes.c_size_t, ctypes.c_size_t,
                ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.ta_pipeline_create_corpus.restype = ctypes.c_void_p
            lib.ta_pipeline_create_corpus.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
                ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ]
        _lib = lib
        log.info("native host runtime loaded: %s", so)
        return _lib


def native_available() -> bool:
    return load_native() is not None


# ---------------------------------------------------------------------------
# RNG fills
# ---------------------------------------------------------------------------


def philox_normal(shape: Sequence[int], seed: int, stream: int = 0) -> np.ndarray:
    """Standard normals, deterministic in (seed, stream)."""
    n = int(np.prod(shape))
    lib = load_native()
    if lib is not None:
        out = np.empty(n, np.float32)
        lib.ta_fill_normal_f32(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, seed & (2**64 - 1), stream & (2**64 - 1),
        )
        return out.reshape(shape)
    gen = np.random.Generator(np.random.Philox(key=seed, counter=[0, 0, 0, stream]))
    return gen.standard_normal(n, dtype=np.float32).reshape(shape)


def philox_tokens(
    shape: Sequence[int], vocab: int, seed: int, stream: int = 0
) -> np.ndarray:
    """Token ids in [0, vocab), deterministic in (seed, stream)."""
    n = int(np.prod(shape))
    lib = load_native()
    if lib is not None:
        out = np.empty(n, np.int32)
        lib.ta_fill_tokens_i32(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, vocab, seed & (2**64 - 1), stream & (2**64 - 1),
        )
        return out.reshape(shape)
    gen = np.random.Generator(np.random.Philox(key=seed, counter=[0, 0, 0, stream]))
    return gen.integers(0, vocab, size=n, dtype=np.int32).reshape(shape)


# ---------------------------------------------------------------------------
# Prefetching batch pipeline
# ---------------------------------------------------------------------------


class _PipelineBase:
    """Shared native-handle lifecycle for the prefetching pipelines.

    Subclasses set ``self._handle`` (or leave it None for the pure-python
    fallback), ``self._elems`` and ``self._out_shape`` before returning from
    ``__init__``, and implement ``_fallback_batch(idx)``. Delivery, the
    stopped-pipeline error path, close, and context-manager/``__del__``
    safety live here once.
    """

    _handle = None  # class default: __del__ is safe pre-__init__
    _fallback_idx = 0

    def next(self) -> np.ndarray:
        if self._handle:
            out = np.empty(self._elems, np.int32)
            idx = self._lib.ta_pipeline_next(
                self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            )
            if idx < 0:
                raise RuntimeError("pipeline stopped")
            return out.reshape(self._out_shape)
        idx = self._fallback_idx
        self._fallback_idx += 1
        return self._fallback_batch(idx)

    def close(self) -> None:
        if self._handle:
            self._lib.ta_pipeline_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        self.close()


class HostDataPipeline(_PipelineBase):
    """Prefetching token-batch source: C++ worker threads fill ahead.

    Batch ``i`` always has the content of ``philox_tokens(shape, vocab,
    seed, stream=i)`` (native stream) regardless of worker count or timing;
    only the prefetch overlap is concurrent, never the content.

    Use as a context manager::

        with HostDataPipeline((B, T), vocab, seed) as pipe:
            for _ in range(steps):
                batch = pipe.next()          # np.int32 (B, T)
    """

    def __init__(
        self,
        batch_shape: Sequence[int],
        vocab: int,
        seed: int,
        *,
        depth: int = 4,
        workers: int = 2,
        start: int = 0,
    ):
        self.batch_shape = tuple(int(s) for s in batch_shape)
        self.vocab = int(vocab)
        self.seed = int(seed)
        self._elems = int(np.prod(self.batch_shape))
        self._out_shape = self.batch_shape
        if self._elems <= 0 or self.vocab <= 0:
            raise ValueError(
                f"bad pipeline config: shape={batch_shape} vocab={vocab}"
            )
        if start < 0:
            raise ValueError(f"start must be >= 0, got {start}")
        self._lib = load_native()
        self._fallback_idx = start
        if self._lib is not None:
            self._handle = self._lib.ta_pipeline_create(
                self._elems, self.vocab, self.seed & (2**64 - 1),
                int(depth), int(workers), int(start),
            )
            if not self._handle:
                raise RuntimeError("ta_pipeline_create failed")

    def _fallback_batch(self, idx: int) -> np.ndarray:
        return philox_tokens(self.batch_shape, self.vocab, self.seed, idx)


# ---------------------------------------------------------------------------
# Memory-mapped token corpus
# ---------------------------------------------------------------------------

_CORPUS_DTYPES = {"int32": (4, np.dtype("<i4")), "uint16": (2, np.dtype("<u2"))}


def _philox4x32(seed: int, ctr_hi: int, ctr_lo: int):
    """Pure-python Philox4x32-10 block, bit-identical to the native one —
    the fallback corpus sampler must pick the same offsets the C++ workers
    would, so native and fallback deliver identical batches."""
    M0, M1 = 0xD2511F53, 0xCD9E8D57
    k0, k1 = seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF
    c = [ctr_lo & 0xFFFFFFFF, (ctr_lo >> 32) & 0xFFFFFFFF,
         ctr_hi & 0xFFFFFFFF, (ctr_hi >> 32) & 0xFFFFFFFF]
    for _ in range(10):
        p0, p1 = M0 * c[0], M1 * c[2]
        c = [((p1 >> 32) ^ c[1] ^ k0) & 0xFFFFFFFF, p1 & 0xFFFFFFFF,
             ((p0 >> 32) ^ c[3] ^ k1) & 0xFFFFFFFF, p0 & 0xFFFFFFFF]
        k0 = (k0 + 0x9E3779B9) & 0xFFFFFFFF
        k1 = (k1 + 0xBB67AE85) & 0xFFFFFFFF
    return c


class TokenCorpus:
    """A flat on-disk array of token ids, memory-mapped (zero-copy reads).

    The native handle mmaps via C++ (``ta_corpus_open``); without the native
    library a ``np.memmap`` serves the same windows with the same
    (bit-identical) Philox offsets. ``fill_batch`` returns ``(rows,
    seqlen+1)`` int32 windows — input and next-token target share the
    buffer. Batch content is a pure function of ``(seed, batch_idx)``.
    """

    def __init__(self, path: str, dtype: str = "int32"):
        self._handle = None
        self._mm = None
        if dtype not in _CORPUS_DTYPES:
            raise ValueError(
                f"dtype must be one of {sorted(_CORPUS_DTYPES)}, got {dtype!r}"
            )
        code, np_dtype = _CORPUS_DTYPES[dtype]
        self.path = path
        self.dtype = dtype
        self._lib = load_native()
        if self._lib is not None and hasattr(self._lib, "ta_corpus_open"):
            self._handle = self._lib.ta_corpus_open(path.encode(), code)
            if not self._handle:
                raise OSError(f"cannot open corpus {path!r} (dtype {dtype})")
            self.n_tokens = int(self._lib.ta_corpus_len(self._handle))
        else:
            self._mm = np.memmap(path, dtype=np_dtype, mode="r")
            self.n_tokens = int(self._mm.shape[0])

    def __len__(self) -> int:
        return self.n_tokens

    def fill_batch(
        self, rows: int, seqlen: int, seed: int, batch_idx: int
    ) -> np.ndarray:
        window = seqlen + 1
        if self.n_tokens < window:
            raise ValueError(
                f"corpus has {self.n_tokens} tokens < one {window}-token window"
            )
        if self._handle:
            out = np.empty(rows * window, np.int32)
            rc = self._lib.ta_corpus_fill_batch(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                rows, seqlen, seed & (2**64 - 1), batch_idx & (2**64 - 1),
            )
            if rc != 0:
                raise RuntimeError("ta_corpus_fill_batch failed")
            return out.reshape(rows, window)
        span = self.n_tokens - window + 1
        out = np.empty((rows, window), np.int32)
        for r in range(rows):
            blk = _philox4x32(seed, batch_idx, r)
            off = ((blk[0] << 32) | blk[1]) % span
            out[r] = self._mm[off:off + window].astype(np.int32)
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.ta_corpus_close(self._handle)
            self._handle = None
        self._mm = None

    def __enter__(self) -> "TokenCorpus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        self.close()


class HostCorpusPipeline(_PipelineBase):
    """Prefetching corpus-batch source: the corpus analogue of
    :class:`HostDataPipeline` (same ordered-window machinery, same
    resume-at-``start`` contract). The corpus must stay open for the
    pipeline's lifetime."""

    def __init__(
        self,
        corpus: TokenCorpus,
        batch: int,
        seq_len: int,
        seed: int,
        *,
        depth: int = 4,
        workers: int = 2,
        start: int = 0,
    ):
        if batch < 1 or seq_len < 1:
            raise ValueError(f"bad pipeline config: batch={batch} seq_len={seq_len}")
        if start < 0:
            raise ValueError(f"start must be >= 0, got {start}")
        self.corpus = corpus
        self.batch = int(batch)
        self.seq_len = int(seq_len)
        self.seed = int(seed)
        self._elems = self.batch * (self.seq_len + 1)
        self._out_shape = (self.batch, self.seq_len + 1)
        self._fallback_idx = start
        self._lib = load_native()
        if (
            self._lib is not None
            and corpus._handle
            and hasattr(self._lib, "ta_pipeline_create_corpus")
        ):
            self._handle = self._lib.ta_pipeline_create_corpus(
                corpus._handle, self.batch, self.seq_len,
                self.seed & (2**64 - 1), int(depth), int(workers), int(start),
            )
            if not self._handle:
                raise RuntimeError("ta_pipeline_create_corpus failed")

    def _fallback_batch(self, idx: int) -> np.ndarray:
        return self.corpus.fill_batch(self.batch, self.seq_len, self.seed, idx)


# ---------------------------------------------------------------------------
# Local process launcher
# ---------------------------------------------------------------------------


def heartbeat() -> None:
    """Mark this rank as making progress (cheap; call once per train step).

    No-op unless the process was launched with heartbeat watching
    (``launch_local(heartbeat_stall=...)`` exports ``TA_HEARTBEAT_FILE``).
    Touching the file is the whole protocol: the supervisor compares its
    mtime against the stall window.
    """
    _HEARTBEATS.inc()  # one flag check when telemetry is off
    path = os.environ.get("TA_HEARTBEAT_FILE")
    if not path:
        return
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        pass  # never let observability kill the workload


def last_launch_attempts() -> int:
    """Attempts used by the most recent :func:`launch_local` in this process
    (1 = no restart was needed). Observability for the elastic path."""
    return _LAST_LAUNCH["attempts"]


_LAST_LAUNCH = {"attempts": 1}


_FAULT_SPEC_CACHE: dict = {}


def _fault_spec():
    """(step, rank) to die at, or None. Parsed from the environment once per
    process — this runs on the production per-step path, and a typo'd
    TA_FAULT_STEP must surface as one clear warning, not a ValueError
    traceback mid-train on every step (ADVICE r3)."""
    raw_step = os.environ.get("TA_FAULT_STEP")
    raw_rank = os.environ.get("TA_FAULT_RANK", "0")
    key = (raw_step, raw_rank)
    if key not in _FAULT_SPEC_CACHE:
        spec = None
        if raw_step is not None:
            try:
                spec = (int(raw_step), int(raw_rank))
            except ValueError:
                log.warning(
                    "fault injection disarmed: unparsable TA_FAULT_STEP=%r / "
                    "TA_FAULT_RANK=%r (expected integers)",
                    raw_step,
                    raw_rank,
                )
        _FAULT_SPEC_CACHE.clear()  # at most one armed spec per process
        _FAULT_SPEC_CACHE[key] = spec
    return _FAULT_SPEC_CACHE[key]


def maybe_inject_fault(step: int) -> None:
    """Fault injection for exercising the supervision/recovery machinery
    (SURVEY §5: the reference has no failure handling at all — a crashed
    rank hangs its peers' allreduce forever).

    Armed by environment, so production runs pay two getenvs and a dict
    lookup per step:

    - ``TA_FAULT_STEP`` (int): the step index at which to die; unset = off.
    - ``TA_FAULT_RANK`` (int, default 0): which rank dies.
    - ``TA_FAULT_ONCE_FILE`` (path, optional): the fault fires only if this
      file exists, and consumes (unlinks) it when it does — so a restarted
      gang does NOT re-crash. This turns an elastic-recovery test into a
      proof of *recovery* (resume + complete) rather than retry-until-luck.

    Dies via ``os._exit(86)`` — no atexit, no JAX teardown — the honest
    shape of a real crash. 86 is distinct from the supervisor's other
    statuses (124 deadline, 125 stall, 128+sig).
    """
    spec = _fault_spec()
    if spec is None or step != spec[0]:
        return
    rank = spec[1]
    try:
        my_rank = int(os.environ.get("JAX_PROCESS_INDEX", "0"))
    except ValueError:
        return  # non-numeric launcher rank: never crash the step loop
    if my_rank != rank:
        return
    once = os.environ.get("TA_FAULT_ONCE_FILE")
    if once:
        try:
            os.unlink(once)
        except FileNotFoundError:
            return  # already fired on a previous attempt
    log.error("fault injection: rank %d exiting at step %d", rank, step)
    if obs.TRACER.active:
        obs.instant("fault_injection", cat="launcher",
                    args={"rank": rank, "step": step})
    obs.TRACER.flush()  # os._exit skips atexit; don't lose the event
    os._exit(86)


def launch_local(
    argv: Sequence[str],
    nprocs: int,
    *,
    timeout: Optional[float] = None,
    grace: float = 2.0,
    failfast: bool = True,
    heartbeat_stall: Optional[float] = None,
    restarts: int = 0,
) -> Tuple[int, List[int]]:
    """Run ``nprocs`` copies of ``argv``, each with ``JAX_PROCESS_INDEX`` /
    ``TA_NUM_PROCESSES`` exported; returns (failure_count, per-rank statuses).

    The reference's ``mp.spawn(main, nprocs=N)`` (``model.py:165``), as an
    exec-based launcher (no fork-inheriting a possibly-initialised JAX) with
    **fail-fast rank supervision**: the first rank to die non-zero gets its
    peers SIGTERMed (SIGKILL after ``grace`` seconds) instead of leaving them
    blocked forever in their next collective — the reference's failure mode
    (a crashed rank deadlocks the allreduce at ``model.py:108``). With
    ``timeout`` set, ranks still running at the deadline are killed and
    report status 124 (the ``timeout(1)`` convention). ``failfast=False``
    restores run-to-completion semantics (every rank's own exit status, no
    peer killing) — for workloads whose ranks are independent.

    ``heartbeat_stall`` (seconds) arms the hang watchdog — the failure the
    crash supervisor cannot see: every rank alive but wedged in a collective
    (SPMD deadlocks stall *all* ranks, so one stalled heartbeat is a
    reliable whole-job symptom). Each rank gets ``TA_HEARTBEAT_FILE``
    exported and should call :func:`heartbeat` as it makes progress (the
    CLI train loop does, once per step); a rank silent for longer than the
    window — counted from launch until its first beat, so size it for jit
    compile — gets the job killed, stalled ranks reporting status **125**
    (vs 124 deadline, 128+sig crash). Requires ``failfast``.

    ``restarts`` arms **elastic recovery**: after a failed attempt (rank
    crash, deadline, heartbeat stall) the whole gang is relaunched with the
    same argv, up to ``restarts`` additional attempts. Whole-gang restart is
    the right granularity for SPMD — a surviving rank is wedged in a
    collective the moment any peer dies, so there is nothing to rejoin. The
    workload must be *resumable*: restore its latest checkpoint on start
    (the CLI train mode's ``--resume`` contract), making a restart a resume
    rather than a redo. ``timeout`` is per attempt. Requires ``failfast``;
    :func:`last_launch_attempts` reports how many attempts the last call
    used. The reference has no recovery story at all — a crashed rank hangs
    its peers' allreduce forever (``model.py:108,163``).
    """
    if nprocs < 1:
        raise ValueError(f"nprocs must be >= 1, got {nprocs}")
    if not failfast and timeout:
        raise ValueError("timeout requires failfast=True")
    if restarts < 0:
        raise ValueError(f"restarts must be >= 0, got {restarts}")
    if restarts and not failfast:
        raise ValueError("restarts requires failfast=True")
    if heartbeat_stall is not None:
        if not failfast:
            raise ValueError("heartbeat_stall requires failfast=True")
        if heartbeat_stall <= 0:
            raise ValueError(
                f"heartbeat_stall must be > 0, got {heartbeat_stall}"
            )
    hb_dir = None
    if heartbeat_stall is not None:
        hb_dir = tempfile.mkdtemp(prefix="ta_hb_")
    _LAST_LAUNCH["attempts"] = 1
    _GANG_LAUNCHES.inc()
    try:
        with obs.span("launch_local", cat="launcher",
                      args=None if not obs.TRACER.active else
                      {"nprocs": nprocs, "restarts": restarts,
                       "watched": heartbeat_stall is not None}):
            failures, statuses = _launch_elastic(
                argv, nprocs, timeout, grace, failfast, heartbeat_stall,
                hb_dir, restarts,
            )
        if obs.REGISTRY.enabled:
            _GANG_ATTEMPTS.inc(_LAST_LAUNCH["attempts"])
            _account_gang_result(statuses)
        return failures, statuses
    finally:
        if hb_dir is not None:
            shutil.rmtree(hb_dir, ignore_errors=True)


def _native_launch_args(argv, nprocs, timeout, grace, heartbeat_stall):
    """ctypes marshalling shared by every native launch entry — one home,
    so conventions (timeout 0 = no deadline, ms floors) cannot diverge
    between the single-attempt and elastic paths."""
    c_argv = (ctypes.c_char_p * (len(argv) + 1))(
        *[a.encode() for a in argv], None
    )
    statuses = (ctypes.c_int * nprocs)()
    timeout_ms = 0 if not timeout else max(1, int(timeout * 1000))
    grace_ms = max(1, int(grace * 1000))
    hb_ms = (
        0 if heartbeat_stall is None else max(1, int(heartbeat_stall * 1000))
    )
    return c_argv, statuses, timeout_ms, grace_ms, hb_ms


def _launch_elastic(
    argv, nprocs, timeout, grace, failfast, heartbeat_stall, hb_dir, restarts
) -> Tuple[int, List[int]]:
    """Dispatch the (possibly restarted) gang launch.

    The native elastic entry runs the whole restart loop in C++; hosts
    without it (or the subprocess fallback) retry in Python around the
    single-attempt impl — same semantics, same per-attempt deadline.
    """
    lib = load_native()
    if (
        restarts
        and lib is not None
        and hasattr(lib, "ta_launch_processes_elastic")
    ):
        c_argv, statuses, timeout_ms, grace_ms, hb_ms = _native_launch_args(
            argv, nprocs, timeout, grace, heartbeat_stall
        )
        attempts = ctypes.c_int(1)
        failures = lib.ta_launch_processes_elastic(
            c_argv, nprocs, timeout_ms, grace_ms,
            hb_dir.encode() if hb_dir is not None else None,
            hb_ms, restarts, statuses, ctypes.byref(attempts),
        )
        if failures < 0:
            raise OSError("fork failed in the native launcher")
        # No summary log here: last_launch_attempts() is the API and the
        # CLI owns the one "recovered after N attempts" message, so native
        # and fallback paths log the same shape. (The fallback additionally
        # logs each failed attempt as it happens — per-attempt visibility
        # the C++ loop cannot provide.)
        _LAST_LAUNCH["attempts"] = attempts.value
        return failures, list(statuses)
    for attempt in range(1, restarts + 2):
        _LAST_LAUNCH["attempts"] = attempt
        failures, statuses = _launch_local_impl(
            argv, nprocs, timeout, grace, failfast, heartbeat_stall, hb_dir
        )
        if failures == 0 or attempt > restarts:
            return failures, statuses
        log.warning(
            "gang attempt %d/%d failed (statuses %s); restarting",
            attempt, restarts + 1, statuses,
        )
        if obs.TRACER.active:
            obs.instant("gang_attempt_failed", cat="launcher",
                        args={"attempt": attempt,
                              "statuses": list(statuses)})
        # Retried attempts' exits must land in the counters too — the
        # caller only accounts the FINAL attempt's statuses, and a stall
        # that elastic recovery papered over is exactly what
        # watchdog_stalls_total exists to surface. (The native C++
        # elastic path runs its retry loop opaquely; its intermediate
        # statuses never reach Python and stay uncounted.)
        _account_gang_result(statuses)
    raise AssertionError("unreachable")


def _launch_local_impl(
    argv, nprocs, timeout, grace, failfast, heartbeat_stall, hb_dir
) -> Tuple[int, List[int]]:
    lib = load_native()
    if lib is not None and (
        heartbeat_stall is None or hasattr(lib, "ta_launch_processes_watched")
    ):
        c_argv, statuses, timeout_ms, grace_ms, hb_ms = _native_launch_args(
            argv, nprocs, timeout, grace, heartbeat_stall
        )
        if heartbeat_stall is not None:
            failures = lib.ta_launch_processes_watched(
                c_argv, nprocs, timeout_ms, grace_ms, hb_dir.encode(), hb_ms,
                statuses,
            )
        elif failfast:
            # timeout in (None, 0) = no deadline, the timeout(1) convention.
            failures = lib.ta_launch_processes_supervised(
                c_argv, nprocs, timeout_ms, grace_ms, statuses,
            )
        else:
            failures = lib.ta_launch_processes(c_argv, nprocs, statuses)
        if failures < 0:
            raise OSError("fork failed in the native launcher")
        return failures, list(statuses)
    # Pure-python fallback, subprocess-based.
    procs = []
    for r in range(nprocs):
        env = dict(os.environ)
        env["JAX_PROCESS_INDEX"] = str(r)
        env["TA_NUM_PROCESSES"] = str(nprocs)
        if hb_dir is not None:
            env["TA_HEARTBEAT_FILE"] = os.path.join(hb_dir, f"hb.{r}")
        procs.append(subprocess.Popen(list(argv), env=env))
    if not failfast:
        sts = [p.wait() for p in procs]
        sts = [128 - s if s < 0 else s for s in sts]
        return sum(1 for s in sts if s != 0), sts
    deadline = None if not timeout else time.monotonic() + timeout
    statuses: List[Optional[int]] = [None] * nprocs
    timed_out = False
    stalled = False
    terminating = False
    kill_at = None
    # Heartbeat tracking, clock-skew-robust: the mtime is only compared
    # against its previous value (a change marks progress) and aged with
    # the monotonic clock — never against wall-clock now, which NTP steps.
    hb_mtime: List[Optional[float]] = [None] * nprocs
    hb_changed = [time.monotonic()] * nprocs
    while any(s is None for s in statuses):
        for i, p in enumerate(procs):
            if statuses[i] is None and p.poll() is not None:
                statuses[i] = p.returncode
                if p.returncode != 0 and not terminating:
                    terminating = True
                    kill_at = time.monotonic() + grace
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
        now = time.monotonic()
        if not terminating and deadline is not None and now >= deadline:
            terminating = True
            timed_out = True
            kill_at = now + grace
            for q in procs:
                if q.poll() is None:
                    q.terminate()
        if not terminating and hb_dir is not None:
            for i, p in enumerate(procs):
                if statuses[i] is not None:
                    continue
                try:
                    m = os.path.getmtime(os.path.join(hb_dir, f"hb.{i}"))
                except OSError:
                    m = None
                if m is not None and m != hb_mtime[i]:
                    hb_mtime[i] = m  # progress = the mtime changed
                    hb_changed[i] = now
                if now - hb_changed[i] >= heartbeat_stall:
                    terminating = True
                    stalled = True
                    kill_at = now + grace
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
                    break
        if terminating and kill_at is not None and now >= kill_at:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            kill_at = now + 60.0
        time.sleep(0.02)
    out = []
    for s in statuses:
        c = s if s is not None else 255
        if c < 0:
            c = 128 - c  # Popen reports -SIGNUM
        if timed_out and c in (128 + signal.SIGTERM, 128 + signal.SIGKILL):
            c = 124
        if stalled and c in (128 + signal.SIGTERM, 128 + signal.SIGKILL):
            c = 125
        out.append(c)
    return sum(1 for c in out if c != 0), out
