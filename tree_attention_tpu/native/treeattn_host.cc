// Host-runtime native library for tree_attention_tpu.
//
// The reference's host-side native substrate is whatever libtorch ships:
// ATen's Philox RNG behind torch.manual_seed (/root/reference/model.py:50)
// and torch.multiprocessing's fork/exec layer behind mp.spawn
// (/root/reference/model.py:165). This library is the TPU framework's own
// equivalent, with no torch in sight:
//
//  - a Philox4x32-10 counter-based RNG (deterministic in (seed, counter),
//    embarrassingly parallel — the same construction ATen uses);
//  - a multi-threaded prefetching batch pipeline: worker threads generate
//    token batches ahead of the consumer into a bounded, strictly-ordered
//    ring (batch i is always delivered i-th, regardless of worker timing),
//    so host data generation overlaps device compute;
//  - a local process launcher: fork/exec N ranks with JAX_PROCESS_INDEX /
//    TA_NUM_PROCESSES exported, wait for all (the mp.spawn shape).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cerrno>
#include <ctime>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include <csignal>
#include <cstdio>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

// ----------------------------------------------------------------------------
// Philox4x32-10 (Salmon et al., "Parallel random numbers: as easy as 1, 2, 3")
// ----------------------------------------------------------------------------

struct Philox {
  uint32_t key[2];
  uint32_t ctr[4];

  static void round_(uint32_t ctr[4], const uint32_t key[2]) {
    constexpr uint64_t M0 = 0xD2511F53ull, M1 = 0xCD9E8D57ull;
    const uint64_t p0 = M0 * static_cast<uint64_t>(ctr[0]);
    const uint64_t p1 = M1 * static_cast<uint64_t>(ctr[2]);
    const uint32_t c0 = static_cast<uint32_t>(p1 >> 32) ^ ctr[1] ^ key[0];
    const uint32_t c1 = static_cast<uint32_t>(p1);
    const uint32_t c2 = static_cast<uint32_t>(p0 >> 32) ^ ctr[3] ^ key[1];
    const uint32_t c3 = static_cast<uint32_t>(p0);
    ctr[0] = c0; ctr[1] = c1; ctr[2] = c2; ctr[3] = c3;
  }

  // One 10-round block for (seed, counter128): fills out[4].
  static void block(uint64_t seed, uint64_t ctr_hi, uint64_t ctr_lo,
                    uint32_t out[4]) {
    uint32_t key[2] = {static_cast<uint32_t>(seed),
                       static_cast<uint32_t>(seed >> 32)};
    uint32_t ctr[4] = {static_cast<uint32_t>(ctr_lo),
                       static_cast<uint32_t>(ctr_lo >> 32),
                       static_cast<uint32_t>(ctr_hi),
                       static_cast<uint32_t>(ctr_hi >> 32)};
    for (int i = 0; i < 10; ++i) {
      round_(ctr, key);
      key[0] += 0x9E3779B9u;  // golden-ratio Weyl bumps
      key[1] += 0xBB67AE85u;
    }
    std::memcpy(out, ctr, sizeof(ctr));
  }
};

inline float u32_to_unit_float(uint32_t x) {
  // (0, 1]: never 0, safe for log().
  return (static_cast<float>(x >> 8) + 1.0f) * (1.0f / 16777216.0f);
}

void fill_u32(uint32_t* out, size_t n, uint64_t seed, uint64_t stream) {
  uint32_t blk[4];
  size_t i = 0;
  for (uint64_t c = 0; i < n; ++c) {
    Philox::block(seed, stream, c, blk);
    for (int j = 0; j < 4 && i < n; ++j) out[i++] = blk[j];
  }
}

}  // namespace

extern "C" {

// Fill `out[n]` with uint32s from the (seed, stream) Philox stream.
void ta_fill_u32(uint32_t* out, size_t n, uint64_t seed, uint64_t stream) {
  fill_u32(out, n, seed, stream);
}

// Fill `out[n]` with standard normals (Box-Muller over Philox uniforms).
void ta_fill_normal_f32(float* out, size_t n, uint64_t seed, uint64_t stream) {
  uint32_t blk[4];
  size_t i = 0;
  for (uint64_t c = 0; i < n; ++c) {
    Philox::block(seed, stream, c, blk);
    for (int j = 0; j < 4 && i < n; j += 2) {
      const float u1 = u32_to_unit_float(blk[j]);
      const float u2 = u32_to_unit_float(blk[j + 1]);
      const float r = std::sqrt(-2.0f * std::log(u1));
      const float t = 6.28318530717958647692f * u2;
      out[i++] = r * std::cos(t);
      if (i < n && j + 1 < 4) out[i++] = r * std::sin(t);
    }
  }
}

// Fill `out[n]` with token ids in [0, vocab) (rejection-free modulo; bias is
// negligible for vocab << 2^32 and irrelevant for synthetic LM data).
void ta_fill_tokens_i32(int32_t* out, size_t n, uint32_t vocab, uint64_t seed,
                        uint64_t stream) {
  std::vector<uint32_t> buf(n);
  fill_u32(buf.data(), n, seed, stream);
  for (size_t i = 0; i < n; ++i)
    out[i] = static_cast<int32_t>(buf[i] % vocab);
}

// ----------------------------------------------------------------------------
// Memory-mapped token corpus
// ----------------------------------------------------------------------------

// A corpus is a flat little-endian array of token ids on disk, memory-mapped
// read-only (the OS page cache is the working set — no user-space copy of
// the file). dtype_code selects the on-disk width: 4 = int32, 2 = uint16
// (the common packed-tokenizer format). Sampling is counter-based: row r of
// batch b starts at Philox(seed, b, r) mod (len − seqlen), so batch content
// is a pure function of (seed, index) — the same structural reproducibility
// contract as the synthetic pipeline, and what makes checkpoint resume
// exact (resume at step k ⇒ identical batch k).
struct TaCorpus {
  void* base = nullptr;
  size_t bytes = 0;
  int64_t n_tokens = 0;
  int dtype_code = 4;
  int fd = -1;
};

extern "C" {

TaCorpus* ta_corpus_open(const char* path, int dtype_code) {
  if (dtype_code != 4 && dtype_code != 2) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* c = new TaCorpus;
  c->base = base;
  c->bytes = static_cast<size_t>(st.st_size);
  c->n_tokens = static_cast<int64_t>(c->bytes) / dtype_code;
  c->dtype_code = dtype_code;
  c->fd = fd;
  return c;
}

int64_t ta_corpus_len(const TaCorpus* c) { return c ? c->n_tokens : -1; }

void ta_corpus_close(TaCorpus* c) {
  if (!c) return;
  munmap(c->base, c->bytes);
  close(c->fd);
  delete c;
}

// Fill out[rows*(seqlen+1)] with `rows` length-(seqlen+1) windows (input and
// next-token target share the buffer). Returns 0, or -1 if the corpus is
// shorter than one window.
int ta_corpus_fill_batch(const TaCorpus* c, int32_t* out, size_t rows,
                         size_t seqlen, uint64_t seed, uint64_t batch_idx) {
  const int64_t window = static_cast<int64_t>(seqlen) + 1;
  if (!c || c->n_tokens < window) return -1;
  const uint64_t span = static_cast<uint64_t>(c->n_tokens - window + 1);
  for (size_t r = 0; r < rows; ++r) {
    uint32_t blk[4];
    Philox::block(seed, batch_idx, r, blk);
    const uint64_t rnd = (static_cast<uint64_t>(blk[0]) << 32) | blk[1];
    const int64_t off = static_cast<int64_t>(rnd % span);
    int32_t* dst = out + r * window;
    if (c->dtype_code == 4) {
      const int32_t* src = static_cast<const int32_t*>(c->base) + off;
      std::memcpy(dst, src, window * sizeof(int32_t));
    } else {
      const uint16_t* src = static_cast<const uint16_t*>(c->base) + off;
      for (int64_t i = 0; i < window; ++i)
        dst[i] = static_cast<int32_t>(src[i]);
    }
  }
  return 0;
}

}  // extern "C"

// ----------------------------------------------------------------------------
// Prefetching batch pipeline
// ----------------------------------------------------------------------------

struct TaPipeline {
  size_t batch_elems;
  uint32_t vocab;
  uint64_t seed;
  size_t depth;
  // Corpus mode: non-null switches workers from synthetic Philox tokens to
  // mmap'd corpus windows of shape (rows, seqlen+1). The corpus handle is
  // borrowed — the caller keeps it open for the pipeline's lifetime.
  const TaCorpus* corpus = nullptr;
  size_t rows = 0;
  size_t seqlen = 0;
  std::vector<std::thread> workers;

  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for ready[head]
  std::condition_variable cv_space;   // workers wait for room in the window
  std::map<uint64_t, std::vector<int32_t>> ready;
  std::atomic<uint64_t> next_claim{0};
  uint64_t head = 0;
  bool stop = false;

  void worker() {
    for (;;) {
      uint64_t idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] {
          return stop || next_claim.load() < head + depth;
        });
        if (stop) return;
        idx = next_claim.fetch_add(1);
      }
      std::vector<int32_t> batch(batch_elems);
      // Content depends only on (seed, idx): worker count/timing never
      // changes what batch `idx` contains — reproducibility is structural.
      if (corpus)
        ta_corpus_fill_batch(corpus, batch.data(), rows, seqlen, seed, idx);
      else
        ta_fill_tokens_i32(batch.data(), batch_elems, vocab, seed, idx);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (stop) return;
        ready.emplace(idx, std::move(batch));
      }
      cv_ready.notify_all();
    }
  }
};

// `start` = index of the first batch delivered (resume support: batch
// content is a pure function of (seed, index), so resuming at step k just
// starts the window there).
TaPipeline* ta_pipeline_create(size_t batch_elems, uint32_t vocab,
                               uint64_t seed, int depth, int n_workers,
                               uint64_t start) {
  if (batch_elems == 0 || vocab == 0 || depth < 1 || n_workers < 1)
    return nullptr;
  auto* p = new TaPipeline;
  p->batch_elems = batch_elems;
  p->vocab = vocab;
  p->seed = seed;
  p->depth = static_cast<size_t>(depth);
  p->next_claim.store(start);
  p->head = start;
  for (int i = 0; i < n_workers; ++i)
    p->workers.emplace_back([p] { p->worker(); });
  return p;
}

// Corpus-backed pipeline: batches of shape (rows, seqlen+1) sampled from an
// open corpus. The corpus must outlive the pipeline.
TaPipeline* ta_pipeline_create_corpus(TaCorpus* corpus, size_t rows,
                                      size_t seqlen, uint64_t seed, int depth,
                                      int n_workers, uint64_t start) {
  if (!corpus || rows == 0 || seqlen == 0 || depth < 1 || n_workers < 1)
    return nullptr;
  if (corpus->n_tokens < static_cast<int64_t>(seqlen) + 1) return nullptr;
  auto* p = new TaPipeline;
  p->batch_elems = rows * (seqlen + 1);
  p->vocab = 0;
  p->seed = seed;
  p->depth = static_cast<size_t>(depth);
  p->corpus = corpus;
  p->rows = rows;
  p->seqlen = seqlen;
  p->next_claim.store(start);
  p->head = start;
  for (int i = 0; i < n_workers; ++i)
    p->workers.emplace_back([p] { p->worker(); });
  return p;
}

// Copy the next in-order batch into out[batch_elems]; returns its index.
int64_t ta_pipeline_next(TaPipeline* p, int32_t* out) {
  std::vector<int32_t> batch;
  uint64_t idx;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    idx = p->head;
    p->cv_ready.wait(lk, [&] { return p->stop || p->ready.count(idx); });
    if (p->stop) return -1;
    batch = std::move(p->ready[idx]);
    p->ready.erase(idx);
    p->head = idx + 1;
  }
  p->cv_space.notify_all();
  std::memcpy(out, batch.data(), p->batch_elems * sizeof(int32_t));
  return static_cast<int64_t>(idx);
}

void ta_pipeline_destroy(TaPipeline* p) {
  if (!p) return;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_space.notify_all();
  p->cv_ready.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

// ----------------------------------------------------------------------------
// Local process launcher (the mp.spawn shape)
// ----------------------------------------------------------------------------

extern char** environ;

// Fork/exec `nprocs` copies of argv (NULL-terminated), each with
// JAX_PROCESS_INDEX=<rank> and TA_NUM_PROCESSES=<nprocs> exported. Blocks
// until all exit; writes each child's exit status into statuses[nprocs].
// Returns the number of children with nonzero status (or -1 on fork failure).
//
// The caller is typically multithreaded (JAX runtime / pipeline workers), so
// the child between fork() and exec must only make async-signal-safe calls:
// each rank's environment array is fully built in the parent; the child does
// nothing but execvpe + _exit.
// Supervised variant: fail-fast rank monitoring. Polls all ranks; when one
// exits nonzero (or is signalled) the rest get SIGTERM, then SIGKILL after
// grace_ms — so a crashed rank cannot leave its peers hung in a collective
// (the reference's failure mode: any rank crash deadlocks the NCCL
// allreduce forever, /root/reference/model.py:108). timeout_ms > 0 bounds
// the whole run; expiry kills every rank and reports status 124 for the
// still-running ones (the `timeout(1)` convention). timeout_ms == 0 means
// no deadline. Returns the number of nonzero statuses, -1 on fork failure.
static int64_t ta_now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Shared launcher machinery. failfast == 0 restores run-to-completion
// semantics: every rank runs to its own exit, no peer killing, no deadline —
// the contract of the plain ta_launch_processes API (ranks whose work is
// independent must each report their own status).
//
// hb_dir != nullptr enables heartbeat stall detection (the failure mode the
// crash supervisor cannot see: every rank alive but one wedged inside a
// collective — an SPMD deadlock makes *all* peers stop heartbeating, so any
// single stalled file is a reliable whole-job symptom). Each rank gets
// TA_HEARTBEAT_FILE=<hb_dir>/hb.<rank> exported; the workload touches that
// file as it makes progress (utime/close — see host_runtime.heartbeat).
// Detection is clock-skew-robust: the mtime is only compared against its
// *previous value* (a change marks progress) and aged with the monotonic
// clock — never against wall-clock "now", which NTP can step. A rank whose
// file hasn't changed (counting from launch, so size the window for jit
// compile) for hb_stall_ms gets the whole job terminated; ranks killed by
// the watchdog report 125, distinct from crash (128+sig) and deadline (124).
static int ta_launch_common(const char* const* argv, int nprocs,
                            int timeout_ms, int grace_ms, int failfast,
                            const char* hb_dir, int hb_stall_ms,
                            int* statuses) {
  std::vector<pid_t> pids(nprocs);

  // Parent-side env construction (one array per rank).
  std::vector<std::vector<std::string>> env_strs(nprocs);
  std::vector<std::vector<char*>> envps(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    for (char** e = environ; *e; ++e) {
      if (std::strncmp(*e, "JAX_PROCESS_INDEX=", 18) == 0) continue;
      if (std::strncmp(*e, "TA_NUM_PROCESSES=", 17) == 0) continue;
      env_strs[r].emplace_back(*e);
    }
    env_strs[r].emplace_back("JAX_PROCESS_INDEX=" + std::to_string(r));
    env_strs[r].emplace_back("TA_NUM_PROCESSES=" + std::to_string(nprocs));
    if (hb_dir)
      env_strs[r].emplace_back(std::string("TA_HEARTBEAT_FILE=") + hb_dir +
                               "/hb." + std::to_string(r));
    for (auto& s : env_strs[r]) envps[r].push_back(const_cast<char*>(s.c_str()));
    envps[r].push_back(nullptr);
  }

  for (int r = 0; r < nprocs; ++r) {
    pid_t pid = fork();
    if (pid < 0) {
      // SIGKILL, not SIGTERM: nothing graceful is owed on a failed launch,
      // and a rank that catches/masks SIGTERM would block the reap below
      // forever. Reaping matters: a long-lived host process accumulating
      // zombies from failed launches would exhaust the pid table.
      for (int k = 0; k < r; ++k) kill(pids[k], SIGKILL);
      for (int k = 0; k < r; ++k) {
        int st = 0;
        while (waitpid(pids[k], &st, 0) < 0 && errno == EINTR) {}
      }
      return -1;
    }
    if (pid == 0) {
      execvpe(argv[0], const_cast<char* const*>(argv), envps[r].data());
      _exit(127);  // exec failed
    }
    pids[r] = pid;
  }
  // Supervision loop: reap OUR children as they exit (polling each own pid
  // — waitpid(-1) would steal statuses of unrelated children the caller's
  // other threads, e.g. pipeline workers, are waiting on); fail-fast on the
  // first nonzero status when requested; enforce the deadline. -1 in `code`
  // marks "still running".
  std::vector<int> code(nprocs, -1);
  const int64_t t0 = ta_now_ms();
  // Heartbeat tracking: last observed mtime (ns; -1 = never seen) and the
  // monotonic time that value last *changed*.
  std::vector<int64_t> hb_mtime(nprocs, -1);
  std::vector<int64_t> hb_changed(nprocs, t0);
  int64_t kill_deadline = -1;  // set once termination has been requested
  bool terminating = false;
  bool timed_out = false;
  bool stalled = false;
  int remaining = nprocs;
  while (remaining > 0) {
    bool reaped = false;
    for (int r = 0; r < nprocs; ++r) {
      if (code[r] >= 0) continue;
      int st = 0;
      pid_t w = waitpid(pids[r], &st, WNOHANG);
      if (w < 0 && errno == EINTR) continue;
      if (w < 0 && errno == ECHILD) {
        // Someone else reaped this child (a waitpid(-1) elsewhere in the
        // process, or SIGCHLD set to SIG_IGN). Its true status is lost;
        // record 255 rather than polling a nonexistent pid forever.
        code[r] = 255;
      } else if (w == pids[r]) {
        code[r] = WIFEXITED(st) ? WEXITSTATUS(st) : 128 + WTERMSIG(st);
      } else {
        continue;
      }
      reaped = true;
      --remaining;
      if (failfast && code[r] != 0 && !terminating) {
        // Fail fast: peers of a dead rank would block in their next
        // collective forever. (The stolen-status path counts too — an
        // unknown exit is not a clean one.)
        terminating = true;
        kill_deadline = ta_now_ms() + grace_ms;
        for (int k = 0; k < nprocs; ++k)
          if (code[k] < 0) kill(pids[k], SIGTERM);
      }
    }
    if (reaped) continue;
    // No child ready: check deadlines, then sleep briefly.
    const int64_t now = ta_now_ms();
    if (!terminating && timeout_ms > 0 && now - t0 >= timeout_ms) {
      terminating = true;
      timed_out = true;
      kill_deadline = now + grace_ms;
      for (int k = 0; k < nprocs; ++k)
        if (code[k] < 0) kill(pids[k], SIGTERM);
    }
    if (!terminating && hb_dir && hb_stall_ms > 0) {
      for (int r = 0; r < nprocs && !terminating; ++r) {
        if (code[r] >= 0) continue;
        struct stat st;
        const std::string path =
            std::string(hb_dir) + "/hb." + std::to_string(r);
        if (stat(path.c_str(), &st) == 0) {
          const int64_t m =
              static_cast<int64_t>(st.st_mtim.tv_sec) * 1000000000 +
              st.st_mtim.tv_nsec;
          if (m != hb_mtime[r]) {  // progress = the mtime *changed*
            hb_mtime[r] = m;
            hb_changed[r] = now;
          }
        }
        // Before the first beat hb_changed holds the launch time, so a
        // rank that never starts heartbeating (crash-looped import, wedged
        // device init) is caught by the same window.
        if (now - hb_changed[r] >= hb_stall_ms) {
          terminating = true;
          stalled = true;
          kill_deadline = now + grace_ms;
          for (int k = 0; k < nprocs; ++k)
            if (code[k] < 0) kill(pids[k], SIGTERM);
        }
      }
    }
    if (terminating && now >= kill_deadline) {
      for (int k = 0; k < nprocs; ++k)
        if (code[k] < 0) kill(pids[k], SIGKILL);
      kill_deadline = now + 60000;  // SIGKILL cannot be ignored; reap soon
    }
    struct timespec nap = {0, 20 * 1000 * 1000};  // 20 ms
    nanosleep(&nap, nullptr);
  }
  int failures = 0;
  for (int r = 0; r < nprocs; ++r) {
    int c = code[r] < 0 ? 255 : code[r];
    // Ranks killed by the deadline report 124 (the timeout(1) convention)
    // and ranks killed by the heartbeat watchdog report 125, rather than
    // 128+SIGTERM/KILL, so callers can tell "hung past the deadline" and
    // "stopped making progress" from "crashed".
    if (timed_out && (c == 128 + SIGTERM || c == 128 + SIGKILL)) c = 124;
    if (stalled && (c == 128 + SIGTERM || c == 128 + SIGKILL)) c = 125;
    if (statuses) statuses[r] = c;
    if (c != 0) ++failures;
  }
  return failures;
}

// Run-to-completion: every rank's own exit status, no peer killing, no
// deadline.
int ta_launch_processes(const char* const* argv, int nprocs, int* statuses) {
  return ta_launch_common(argv, nprocs, 0, 2000, /*failfast=*/0,
                          /*hb_dir=*/nullptr, 0, statuses);
}

// Supervised variant: fail-fast rank monitoring (see the comment block
// above ta_launch_common).
int ta_launch_processes_supervised(const char* const* argv, int nprocs,
                                   int timeout_ms, int grace_ms,
                                   int* statuses) {
  return ta_launch_common(argv, nprocs, timeout_ms, grace_ms,
                          /*failfast=*/1, /*hb_dir=*/nullptr, 0, statuses);
}

// Watched variant: fail-fast plus heartbeat stall detection (see the
// comment block above ta_launch_common).
int ta_launch_processes_watched(const char* const* argv, int nprocs,
                                int timeout_ms, int grace_ms,
                                const char* hb_dir, int hb_stall_ms,
                                int* statuses) {
  return ta_launch_common(argv, nprocs, timeout_ms, grace_ms,
                          /*failfast=*/1, hb_dir, hb_stall_ms, statuses);
}

// Elastic variant: fail-fast supervision with bounded whole-gang restart.
// On a failed attempt (rank crash, deadline, heartbeat stall) the gang is
// torn down by the fail-fast machinery and the SAME argv is re-exec'd, up
// to max_restarts additional attempts. Whole-gang restart is the right
// granularity for SPMD: a surviving rank is wedged in a collective the
// moment any peer dies, so there is nothing to rejoin — the workload is
// expected to be resumable (restore its latest checkpoint on start; the
// CLI's --resume contract). timeout_ms is a PER-ATTEMPT deadline. The
// heartbeat stall window restarts from each attempt's launch. statuses
// holds the LAST attempt's ranks; *attempts (if non-null) receives the
// number of attempts run. A launch-machinery failure (fork: rc -1) is not
// retried — the host is sick, not the gang.
int ta_launch_processes_elastic(const char* const* argv, int nprocs,
                                int timeout_ms, int grace_ms,
                                const char* hb_dir, int hb_stall_ms,
                                int max_restarts, int* statuses,
                                int* attempts) {
  int failures = -1;
  int attempt = 0;
  for (;;) {
    ++attempt;
    failures = ta_launch_common(argv, nprocs, timeout_ms, grace_ms,
                                /*failfast=*/1, hb_dir, hb_stall_ms,
                                statuses);
    if (failures <= 0 || attempt > max_restarts) break;
  }
  if (attempts) *attempts = attempt;
  return failures;
}

}  // extern "C"
