"""Data layer: synthetic Q/KV and LM-batch generation, shard-local.

TPU-native replacement for the reference's ``make_data``
(``/root/reference/model.py:37-56``), which seeds torch's RNG with
``0 + rank`` so each rank draws a *different* KV block — that per-rank seed is
the reference's entire sequence-parallel sharding story. Here the same
semantics come from ``jax.random.fold_in(key, shard_index)``: deterministic,
order-independent, and collision-free per shard.

Two equivalent constructions, tested against each other:

- :func:`make_qkv` — host/global form: concatenates the per-shard blocks, so
  ``n_shards`` only changes *which* random blocks compose the sequence, never
  the contract.
- :func:`make_qkv_sharded` — mesh form: each device generates **its own** KV
  block inside ``shard_map`` (fold_in on ``axis_index``), so a million-token
  cache is born sharded — no host materialisation, no device-0 hotspot. The
  reference instead re-runs ``make_data`` per process (``model.py:145``).

Layout note: the reference creates ``(B, T, nh, C)`` but its kernel assumes
``(B, nh, T, C)`` — the confirmed bug 1 of SURVEY.md §2.1. This framework has
exactly one layout, ``(B, H, T, D)``, everywhere.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tree_attention_tpu.parallel.mesh import AXIS_DATA, AXIS_SEQ
from tree_attention_tpu.utils.config import RunConfig

from tree_attention_tpu.parallel.compat import shard_map

# Single source of truth for the canonical (reference) workload defaults.
_REF = RunConfig()

_Q, _K, _V = 1, 2, 3  # stream tags folded into the key, one per tensor


def _block(key: jax.Array, tag: int, shard: jax.Array | int,
           shape: Tuple[int, ...], dtype) -> jax.Array:
    """The one definition of a random block: fold (tag, shard) into the key."""
    k = jax.random.fold_in(jax.random.fold_in(key, tag), shard)
    return jax.random.normal(k, shape, dtype)


def make_qkv(
    key: jax.Array,
    *,
    batch: int = _REF.batch,
    heads: int = _REF.heads,
    kv_heads: Optional[int] = None,
    q_len: int = _REF.q_len,
    seq_len: int = _REF.seq_len,
    head_dim: int = _REF.head_dim,
    dtype=jnp.bfloat16,
    n_shards: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Global-form Q/KV: K/V are ``n_shards`` concatenated fold_in blocks.

    Defaults are the reference workload (``model.py:140-145``): B=1, 16 heads,
    head_dim 128, 64k context, single-query decode.
    """
    kv_heads = heads if kv_heads is None else kv_heads
    if seq_len % n_shards:
        raise ValueError(f"seq_len {seq_len} not divisible by {n_shards} shards")
    t_local = seq_len // n_shards
    q = _block(key, _Q, 0, (batch, heads, q_len, head_dim), dtype)
    ks = [_block(key, _K, s, (batch, kv_heads, t_local, head_dim), dtype)
          for s in range(n_shards)]
    vs = [_block(key, _V, s, (batch, kv_heads, t_local, head_dim), dtype)
          for s in range(n_shards)]
    return q, jnp.concatenate(ks, axis=2), jnp.concatenate(vs, axis=2)


def make_qkv_sharded(
    key: jax.Array,
    mesh: Mesh,
    *,
    batch: int = _REF.batch,
    heads: int = _REF.heads,
    kv_heads: Optional[int] = None,
    q_len: int = _REF.q_len,
    seq_len: int = _REF.seq_len,
    head_dim: int = _REF.head_dim,
    dtype=jnp.bfloat16,
    seq_axis: str = AXIS_SEQ,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Mesh-form Q/KV: KV born sharded along ``seq_axis``, Q replicated.

    Bit-identical to :func:`make_qkv` with ``n_shards = mesh.shape[seq_axis]``
    (same fold_in blocks, generated on the devices that own them).
    """
    kv_heads = heads if kv_heads is None else kv_heads
    n = mesh.shape[seq_axis]
    if seq_len % n:
        raise ValueError(f"seq_len {seq_len} not divisible by mesh axis {n}")
    t_local = seq_len // n
    gen = _sharded_gen(
        mesh, seq_axis, batch, heads, kv_heads, q_len, t_local, head_dim,
        jnp.dtype(dtype).name,
    )
    return gen(key)


@functools.lru_cache(maxsize=64)
def _sharded_gen(mesh, seq_axis, batch, heads, kv_heads, q_len, t_local,
                 head_dim, dtype_name):
    """Jitted per-shard generator, cached so config sweeps don't recompile."""
    dtype = jnp.dtype(dtype_name)
    q_spec = P()
    kv_spec = P(None, None, seq_axis, None)

    def _gen(key):
        shard = lax.axis_index(seq_axis)
        q = _block(key, _Q, 0, (batch, heads, q_len, head_dim), dtype)
        k = _block(key, _K, shard, (batch, kv_heads, t_local, head_dim), dtype)
        v = _block(key, _V, shard, (batch, kv_heads, t_local, head_dim), dtype)
        return q, k, v

    return jax.jit(shard_map(
        _gen, mesh=mesh, in_specs=P(),
        out_specs=(q_spec, kv_spec, kv_spec), check_vma=False,
    ))


def make_lm_batch(
    key: jax.Array,
    *,
    batch: int,
    seq_len: int,
    vocab_size: int,
    mesh: Optional[Mesh] = None,
    data_axis: str = AXIS_DATA,
    seq_axis: str = AXIS_SEQ,
) -> Dict[str, jax.Array]:
    """Random next-token LM batch: ``targets`` = ``inputs`` shifted left.

    With a mesh, the batch is placed sharded (batch dim over ``data_axis``,
    sequence dim over ``seq_axis`` when those axes exist).
    """
    tokens = jax.random.randint(key, (batch, seq_len + 1), 0, vocab_size)
    out = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
    if mesh is not None:
        for dim, name, size in (("batch", data_axis, batch),
                                ("seq_len", seq_axis, seq_len)):
            if name in mesh.shape and size % mesh.shape[name]:
                raise ValueError(
                    f"{dim}={size} not divisible by mesh axis "
                    f"'{name}'={mesh.shape[name]}"
                )
        spec = P(
            data_axis if data_axis in mesh.shape else None,
            seq_axis if seq_axis in mesh.shape else None,
        )
        out = {k: jax.device_put(v, NamedSharding(mesh, spec))
               for k, v in out.items()}
    return out
