"""Parallelism layer: mesh/runtime + sequence-parallel attention algorithms."""

from tree_attention_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    cpu_mesh,
    initialize_distributed,
    make_mesh,
    prune_axes,
    replicate,
    shard_along,
)
from tree_attention_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
    ring_decode,
)
from tree_attention_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_decode,
)
from tree_attention_tpu.parallel.tree import (  # noqa: F401
    MERGE_PAYLOAD_FORMATS,
    resolve_merge_payload,
    shard_zigzag,
    tree_attention,
    tree_decode,
    tree_decode_q8,
    unshard_zigzag,
    zigzag_perm,
)
