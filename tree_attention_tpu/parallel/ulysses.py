"""Ulysses (all-to-all head-swap) sequence parallelism.

The third sequence-parallel family, alongside the tree reduction
(:mod:`tree_attention_tpu.parallel.tree`) and the ring comparator
(:mod:`tree_attention_tpu.parallel.ring`). The reference implements none of
them but positions tree against ring (SURVEY.md §2.4); Ulysses is included
because the three families trade communication *shape*, and a framework
claiming the sequence-parallel capability should let the deployment pick:

- **tree**: KV stay resident; Q rides a chunked all-gather and the merge is
  an O(log N) collective of O(B·H·Tq·D) safe-softmax partials. Best when
  the merge payload is small relative to KV (decode, GQA).
- **ring**: KV shards rotate N−1 hops of O(local KV) each, overlapped with
  compute. Latency chain O(N), payload KV-only.
- **ulysses** (this module): ONE ``all_to_all`` re-shards sequence→heads,
  each device runs *full-sequence* attention for ``H/N`` heads with the
  plain single-device kernel (no cross-device softmax state at all), and
  one ``all_to_all`` re-shards the output back. Payload is Q+K+V+O (not
  KV-only), but the collective count is constant and the local kernel sees
  the whole sequence — no per-shard masking geometry, no merge monoid.
  Requires ``Hq % N == 0`` and ``Hkv % N == 0``.

Differentiable end-to-end: ``all_to_all`` transposes to the inverse
``all_to_all``, and the local kernel is the custom-VJP
:func:`tree_attention_tpu.ops.flash_attention`.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tree_attention_tpu.parallel.compat import shard_map

from tree_attention_tpu import obs
from tree_attention_tpu.ops import flash_attention, resolve_impl_for_mesh
from tree_attention_tpu.parallel.accounting import (
    account_payload as _account_payload,
    shard_counts as _shard_counts,
)
from tree_attention_tpu.parallel.mesh import AXIS_SEQ


def ulysses_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    seq_axis: str = AXIS_SEQ,
    data_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    q_position: Optional[int] = None,
    impl: str = "auto",
    block_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Replicated-Q decode via the Ulysses head-swap — the third entry in
    the decode-shape comparator (VERDICT r3 item 1).

    Same contract as :func:`tree_decode
    <tree_attention_tpu.parallel.tree.tree_decode>` and :func:`ring_decode
    <tree_attention_tpu.parallel.ring.ring_decode>`: Q ``(B, Hq, Tq, D)``
    replicated over ``seq_axis``, K/V ``(B, Hkv, Tk, D)`` sequence-sharded
    along dim 2; returns ``(out, lse)`` replicated.

    The family's communication shape is what makes this entry interesting:
    from a sequence-sharded cache, each decode step must ``all_to_all``
    the **entire KV buffer** (seq-sharding → head-sharding, O(Tk·Hkv·D/N)
    bytes per device) before the purely local full-context kernel runs,
    then ``all_gather`` the O(B·Hq·Tq·D) head-slice outputs. Tree and ring
    move O(B·H·Tq·D) *independent of context length*; Ulysses' per-step
    wire volume grows linearly with the context — the founding claim of
    the tree merge, made measurable (``bench/comm.py`` counts both).
    Requires ``Hq % N == 0`` and ``Hkv % N == 0``.
    """
    B, Hq, Tq, D = q.shape
    Hkv, Tk_global = k.shape[1], k.shape[2]
    if q_position is None:
        q_position = Tk_global - Tq
    n = mesh.shape[seq_axis]
    if Tk_global % n:
        raise ValueError(
            f"global KV length {Tk_global} must divide over {n} "
            f"'{seq_axis}' shards"
        )
    # Like ulysses_attention: with a head-parallel axis in play the
    # all-to-all splits the PER-SHARD head slice, so validate the local
    # counts, not the global ones.
    h_shards = mesh.shape[head_axis] if head_axis is not None else 1
    if Hq % h_shards or Hkv % h_shards:
        raise ValueError(
            f"heads (q={Hq}, kv={Hkv}) must divide over {h_shards} "
            f"'{head_axis}' shards"
        )
    if (Hq // h_shards) % n or (Hkv // h_shards) % n:
        raise ValueError(
            f"ulysses re-shards the head dim: per-shard heads "
            f"(q={Hq // h_shards}, kv={Hkv // h_shards}) must divide over "
            f"{n} '{seq_axis}' shards (use tree/ring decode for head "
            f"counts smaller than the mesh axis)"
        )
    impl = resolve_impl_for_mesh(impl, mesh)

    q_spec = P(data_axis, head_axis, None, None)
    kv_spec = P(data_axis, head_axis, seq_axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=(q_spec, P(data_axis, head_axis, None)),
        check_vma=False,
    )
    def _sharded(q_l, k_l, v_l):
        me = lax.axis_index(seq_axis)
        # seq-sharded -> head-sharded: (B, Hkv, Tk/n, D) -> (B, Hkv/n, Tk, D).
        def to_heads(x):
            return lax.all_to_all(
                x, seq_axis, split_axis=1, concat_axis=2, tiled=True
            )

        kh, vh = to_heads(k_l), to_heads(v_l)
        # Q is replicated over seq (its head dim may still be head-sharded):
        # slice the resident seq-shard's head group from the LOCAL slice.
        g = q_l.shape[1] // n
        qh = lax.dynamic_slice_in_dim(q_l, me * g, g, axis=1)
        out_h, lse_h = flash_attention(
            qh, kh, vh, causal=causal, scale=scale,
            q_offset=q_position, kv_offset=0,
            impl=impl, block_size=block_size,
        )
        # Gather the head slices back to the replicated output contract.
        out = lax.all_gather(out_h, seq_axis, axis=1, tiled=True)
        lse = lax.all_gather(lse_h, seq_axis, axis=1, tiled=True)
        return out.astype(q.dtype), lse.astype(jax.numpy.float32)

    # The family's founding liability, counted: each step all-to-alls the
    # ENTIRE local KV buffer (O(Tk/N) per device — linear in context, where
    # tree/ring move O(B·H·Tq·D)), then gathers back the head-slice
    # (out, lse) partials. Per-device dims: batch over the data axis, heads
    # over the model axis (the seq axis divides KV tokens / head groups).
    d_sh, _ = _shard_counts(mesh, data_axis, None)
    B_l = -(-B // d_sh)
    g = (Hq // h_shards) // n
    _account_payload(
        "ulysses_decode",
        all_to_all=2 * B_l * (Hkv // h_shards) * (Tk_global // n) * D
        * k.dtype.itemsize,
        all_gather=B_l * g * Tq * (D * q.dtype.itemsize + 4),
    )
    with obs.span("ulysses_decode", cat="dispatch",
                  args=None if not obs.TRACER.active else
                  {"ctx": Tk_global, "shards": n}):
        return _sharded(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    seq_axis: str = AXIS_SEQ,
    data_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    q_position: Optional[int] = None,
    impl: str = "auto",
    block_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Sequence-sharded exact attention via the Ulysses head-swap.

    Same contract and sharding as :func:`tree_attention
    <tree_attention_tpu.parallel.tree.tree_attention>` and
    :func:`ring_attention <tree_attention_tpu.parallel.ring.ring_attention>`:
    ``q`` of shape ``(B, Hq, Tq, D)`` and ``k``/``v`` of ``(B, Hkv, Tk, D)``
    sharded along dim 2 over ``seq_axis``; returns ``(out, lse)`` sharded
    like ``q``. ``q_position`` is the global position of q's first row
    (default: suffix-aligned, ``Tk - Tq``).

    Head divisibility is a hard requirement of the family: the all-to-all
    re-shards the head dim, so both ``Hq`` and ``Hkv`` must divide by the
    shard count (use tree/ring otherwise — e.g. GQA with fewer KV heads
    than devices).
    """
    B, Hq, Tq_global, D = q.shape
    Hkv, Tk_global = k.shape[1], k.shape[2]
    if q_position is None:
        q_position = Tk_global - Tq_global
    n = mesh.shape[seq_axis]
    if Tq_global % n or Tk_global % n:
        raise ValueError(
            f"sequence lengths (q={Tq_global}, k={Tk_global}) must divide "
            f"over {n} '{seq_axis}' shards"
        )
    # The all-to-all splits each device's LOCAL head slice, so with a
    # head-parallel axis in play the requirement is on the per-shard head
    # count, not the global one.
    h_shards = mesh.shape[head_axis] if head_axis is not None else 1
    if Hq % h_shards or Hkv % h_shards:
        raise ValueError(
            f"heads (q={Hq}, kv={Hkv}) must divide over {h_shards} "
            f"'{head_axis}' shards"
        )
    if (Hq // h_shards) % n or (Hkv // h_shards) % n:
        raise ValueError(
            f"ulysses re-shards the head dim: per-shard heads "
            f"(q={Hq // h_shards}, kv={Hkv // h_shards}"
            f"{f' after {h_shards}-way head sharding' if h_shards > 1 else ''})"
            f" must divide over {n} '{seq_axis}' shards (use tree/ring "
            f"attention for head counts smaller than the mesh axis)"
        )
    impl = resolve_impl_for_mesh(impl, mesh)

    spec = P(data_axis, head_axis, seq_axis, None)
    lse_spec = P(data_axis, head_axis, seq_axis)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, lse_spec),
        check_vma=False,
    )
    def _sharded(q_l, k_l, v_l):
        # seq-sharded -> head-sharded: (B, H, T/n, D) -> (B, H/n, T, D).
        # One collective per tensor; afterwards each device owns the FULL
        # sequence for its head slice, so the local kernel needs no shard
        # offsets and no cross-device softmax state.
        def to_heads(x):
            return lax.all_to_all(
                x, seq_axis, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = to_heads(q_l), to_heads(k_l), to_heads(v_l)
        out_h, lse_h = flash_attention(
            qh, kh, vh, causal=causal, scale=scale,
            q_offset=q_position, kv_offset=0,
            impl=impl, block_size=block_size,
        )
        # head-sharded -> seq-sharded: (B, H/n, T, D) -> (B, H, T/n, D),
        # and the (B, H/n, T) lse likewise.
        out_l = lax.all_to_all(
            out_h, seq_axis, split_axis=2, concat_axis=1, tiled=True
        )
        lse_l = lax.all_to_all(
            lse_h, seq_axis, split_axis=2, concat_axis=1, tiled=True
        )
        return out_l.astype(q.dtype), lse_l.astype(jax.numpy.float32)

    # Five all-to-alls per step: Q/K/V seq→head, then (out, lse) back.
    # Per-device dims: batch over the data axis, heads over the model axis.
    d_sh, _ = _shard_counts(mesh, data_axis, None)
    B_l = -(-B // d_sh)
    itm = q.dtype.itemsize
    _account_payload(
        "ulysses_attention",
        all_to_all=(
            B_l * (Hq // h_shards) * (Tq_global // n) * D * itm      # q
            + 2 * B_l * (Hkv // h_shards) * (Tk_global // n) * D * itm  # k, v
            + B_l * (Hq // h_shards) * (Tq_global // n) * (D * itm + 4)  # out, lse
        ),
    )
    with obs.span("ulysses_attention", cat="dispatch",
                  args=None if not obs.TRACER.active else
                  {"seq": Tq_global, "shards": n}):
        return _sharded(q, k, v)
