"""Shared collective-payload accounting for the sequence-parallel families.

One home for the telemetry vocabulary all three families (tree, ring,
Ulysses) report in, so the algorithm modules don't reach into each other
for counters: per-device wire bytes by collective kind, and entry-point
dispatch counts. The per-call figures are closed forms over the dispatched
call's static shapes — the running-total companion to ``bench/comm.py``'s
compiled-HLO parse (which remains the per-call ground truth).

Counted where each entry point's Python body runs: per call when eager,
per trace under an enclosing jit (see :mod:`tree_attention_tpu.obs.metrics`
on trace-time semantics).
"""

from __future__ import annotations

from typing import Optional, Tuple

from tree_attention_tpu import obs

PAYLOAD_BYTES = obs.counter(
    "collective_payload_bytes_total",
    "per-device collective operand bytes implied by dispatched calls' "
    "static shapes (trace-time under an enclosing jit)",
    labels=("algorithm", "collective"),
)
DISPATCH = obs.counter(
    "parallel_dispatch_total",
    "sequence-parallel entry-point dispatches (trace-time under an "
    "enclosing jit)",
    labels=("algorithm",),
)


def shard_counts(
    mesh, data_axis: Optional[str], head_axis: Optional[str]
) -> Tuple[int, int]:
    """(data_shards, head_shards) for converting an entry point's GLOBAL
    array dims to the per-device dims its collectives actually move —
    inside ``shard_map`` the operands are already batch/head shards, so
    per-device accounting must divide by any extra mesh axes in play."""

    def size(axis: Optional[str]) -> int:
        return mesh.shape.get(axis, 1) if axis is not None else 1

    return max(size(data_axis), 1), max(size(head_axis), 1)


def account_payload(algorithm: str, **collective_bytes: int) -> None:
    """Record one dispatch's per-device payload bytes by collective kind."""
    if not obs.REGISTRY.enabled:
        return
    DISPATCH.labels(algorithm=algorithm).inc()
    for coll, nbytes in collective_bytes.items():
        PAYLOAD_BYTES.labels(algorithm=algorithm, collective=coll).inc(
            int(nbytes)
        )
